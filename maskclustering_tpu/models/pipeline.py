"""Per-scene end-to-end pipeline: association -> graph -> clustering -> export.

The TPU analog of the reference's per-scene entry (main.py:9-21). Device
stages run under jit with static, bucket-padded shapes. The per-scene
pipeline crosses to host exactly ONCE mid-program:

1. the mask table — compact indices of valid masks materialize at the top
   of the graph stage (the pull drains the associate dispatch; the table's
   M_pad bucket is data-dependent, so this crossing is irreducible).

Two historical mid-program crossings are gone: the observer-percentile
schedule computes on device (`observer_schedule_device`, PR 3), and the
cluster assignment — formerly pulled for the post-process's host routing
prep — now stays device-resident end to end: the device post-process
(models/postprocess_device.py) consumes it in HBM and only the final
compact instance planes drain to host. The remaining crossing is marked
with a ``host_pull`` span attr and counted on ``pipeline.host_sync`` —
the fence-count budget (exactly 1 per scene) is pinned by
tests/test_executor.py and the mct-check IR.SYNC gates.

The pipeline is split into a **device phase** (`run_scene_device`) and a
**host phase** (`run_scene_host`) joined by an explicit `DeviceHandoff`,
so the overlapped scene executor (run.py) can dispatch scene N+1's device
phase while scene N's host tail (DBSCAN split, overlap merge, artifact
export) drains on a worker thread. `run_scene` remains the sequential
composition of the two and is byte-identical to the overlapped execution.
"""

from __future__ import annotations

import logging
from typing import Dict, NamedTuple, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from maskclustering_tpu import obs
from maskclustering_tpu.analysis.transfer_guard import (
    device_phase_guard,
    sanctioned_pull,
)
from maskclustering_tpu.config import PipelineConfig
from maskclustering_tpu.datasets.base import SceneTensors
from maskclustering_tpu.models.backprojection import associate_scene_tensors
from maskclustering_tpu.models.clustering import iterative_clustering
from maskclustering_tpu.models.graph import (
    MaskTable,
    build_mask_table,
    compute_graph_stats,
    observer_schedule_device,
)
from maskclustering_tpu.models.postprocess import SceneObjects, export_artifacts
from maskclustering_tpu.utils import faults

log = logging.getLogger("maskclustering_tpu")


class SceneResult(NamedTuple):
    objects: SceneObjects
    table: MaskTable
    assignment: np.ndarray
    timings: Dict[str, float]
    # mct-sentinel invariant digest (obs/digest.py) — None on paths that
    # opt out; trailing default keeps historical 4-tuple constructors valid
    digest: Optional[Dict] = None


class DeviceHandoff(NamedTuple):
    """Everything the host phase needs from the device phase of one scene.

    The contract: EVERY tensor stays DEVICE-resident — ``assignment``
    (since the drain restructure took host syncs 2 -> 1), ``first_id``/
    ``last_id``/``node_visible``/``active`` — the post-process kernels
    consume them in HBM, and only the final compact instance planes cross
    back. A handoff therefore pins ~2 x (F, N) int16 of HBM (halved from
    the historical int32 planes) until its host phase finishes; the
    overlapped executor bounds the number of live handoffs to one (double
    buffering) for exactly that reason.
    """

    table: MaskTable
    assignment: jnp.ndarray  # (M_pad,) int32, device
    active: jnp.ndarray  # (M_pad,) bool, device — valid & not undersegmented
    node_visible: jnp.ndarray  # (M_pad, F) bool, device
    first_id: jnp.ndarray  # (F, N) int16, device
    last_id: jnp.ndarray  # (F, N) int16, device
    scene_points: np.ndarray  # (N_pad, 3) f32, host (padded)
    frame_ids: Sequence  # padded frame identifiers
    k_max: int
    n_real: int  # true (pre-pad) point count
    seq_name: Optional[str]
    timings: Dict[str, float]  # associate/graph/cluster stage walls


# the fused mesh path's f32 schedule formulation, jitted once per max_len so
# the eager per-scene call doesn't re-dispatch its ~15 tiny ops one by one
_observer_schedule_jit = jax.jit(observer_schedule_device,
                                 static_argnames=("max_len",))


K_MAX_CEILING = 1023


# canonical home is the compile-cache module (bounding distinct jit shapes
# is its hit rate); re-exported here for the scripts/tests that always
# imported it from the pipeline
from maskclustering_tpu.utils.compile_cache import bucket_size  # noqa: E402


def pad_scene_tensors(tensors: SceneTensors, f_pad: int, n_pad: int) -> SceneTensors:
    """Pad a scene to a (F_pad, N_pad) shape bucket.

    Padded frames are invalid (frame_valid=False -> no claims); padded
    points sit at a far sentinel coordinate no frustum reaches within
    depth_trunc (same invariants as the mesh batch path, parallel/batch.py).
    Frame arrays pad in their current residence: device arrays via jnp (the
    bench renders frames directly in HBM), host numpy via np — host frames
    MUST stay host so the compact-feed codec (io/feed.py) still sees them
    before the upload in associate_scene_tensors.
    """
    import dataclasses

    f, n = tensors.num_frames, tensors.num_points
    if f == f_pad and n == n_pad:
        return tensors
    if f_pad < f or n_pad < n:
        raise ValueError(f"bucket ({f_pad}, {n_pad}) smaller than scene ({f}, {n})")
    pts = np.full((n_pad, 3), 1.0e4, dtype=np.float32)
    pts[:n] = tensors.scene_points
    df = f_pad - f

    def pad_frames(arr, constant_values=0.0):
        widths = ((0, df),) + ((0, 0),) * (np.ndim(arr) - 1)
        if isinstance(arr, jnp.ndarray) and not isinstance(arr, np.ndarray):
            return jnp.pad(arr, widths, constant_values=constant_values)
        return np.pad(np.asarray(arr), widths, constant_values=constant_values)

    return dataclasses.replace(
        tensors,
        scene_points=pts,
        depths=pad_frames(tensors.depths),
        segmentations=pad_frames(tensors.segmentations),
        intrinsics=pad_frames(tensors.intrinsics, constant_values=1.0),
        cam_to_world=pad_frames(tensors.cam_to_world, constant_values=0.0),
        frame_valid=np.concatenate([np.asarray(tensors.frame_valid),
                                    np.zeros(df, dtype=bool)]),
        frame_ids=list(tensors.frame_ids) + [None] * df,
    )


def bucket_k_max(max_id: int, minimum: int = 63, ceiling: int = K_MAX_CEILING) -> int:
    """Smallest (2^b - 1) >= max(max_id, minimum): few jit buckets, no aliasing.

    Clamped at ``ceiling``: one corrupt id in a uint16 id-map (e.g. 65535)
    would otherwise blow up the dense f*k_max slot tables and (M,M) matrices.
    Ids above k_max are dropped as background by associate_frame, so a clamp
    degrades gracefully to ignoring the corrupt masks.
    """
    k = minimum
    while k < max_id and k < ceiling:
        k = k * 2 + 1
    if max_id > k:
        log.warning(
            "segmentation ids up to %d exceed k_max ceiling %d; "
            "masks with larger ids are treated as background", max_id, k)
    return k


def run_scene_device(tensors: SceneTensors, cfg: PipelineConfig, *,
                     k_max: Optional[int] = None,
                     seq_name: Optional[str] = None) -> DeviceHandoff:
    """Device phase of one scene: associate -> graph -> cluster.

    ``k_max`` (max mask id per frame) defaults to a power-of-two bucket of the
    scene's true max segmentation id, so crowded frames (CropFormer id-maps
    are uint16) are never truncated while jit recompiles stay rare.

    Stage timing comes from obs spans (obs.scene_tracer()): with obs armed
    every stage is sync-fenced at its boundary (``sp.sync``), so device
    work is attributed to the stage that dispatched it instead of the
    stage that first pulls a result. Disarmed, the spans are timing-only
    and the ONLY blocking points are the pipeline's own two host pulls —
    the associate span then measures dispatch and the graph span absorbs
    the associate drain (arm obs for exact attribution).

    Exactly ONE host sync per scene, marked with a ``host_pull`` span attr
    and counted on ``pipeline.host_sync``:

    - graph start: the mask-valid table materializes (drains associate).

    The cluster assignment no longer syncs here — it rides the handoff as
    a device array and the device post-process consumes it in HBM (its
    routing prep moved on device), so graph -> schedule -> clustering ->
    post-process is one uninterrupted dispatch chain after the mask table.

    Under ``--transfer-guard`` / ``MCT_TRANSFER_GUARD`` (the Family-3
    sanitizer, analysis/transfer_guard.py) the whole phase runs inside
    ``jax.transfer_guard("disallow")`` with only the pull above opened as
    a sanctioned window — any OTHER implicit transfer raises at its
    source line. Off by default; results are identical either way
    (pinned by tests/test_analysis.py).
    """
    with device_phase_guard():
        return _run_scene_device_impl(tensors, cfg, k_max=k_max,
                                      seq_name=seq_name)


def _run_scene_device_impl(tensors: SceneTensors, cfg: PipelineConfig, *,
                           k_max: Optional[int],
                           seq_name: Optional[str]) -> DeviceHandoff:
    timings: Dict[str, float] = {}
    tracer = obs.scene_tracer()
    # fault seam: deterministic injection point for the device phase
    # (utils/faults.FaultPlan); a no-op without an active plan
    faults.inject("device", seq_name)

    if k_max is None:
        from maskclustering_tpu.utils.compile_cache import max_seg_id

        k_max = bucket_k_max(max_seg_id(tensors.segmentations))

    n_real = tensors.num_points
    with tracer.span("associate", scene=seq_name, k_max=k_max,
                     num_frames=tensors.num_frames, num_points=n_real) as sp:
        if cfg.use_exact_ball_query:
            # host-only parity path: no jit shape buckets, padding would only
            # add pointless device round-trips
            from maskclustering_tpu.models.exact_backprojection import associate_scene_exact

            assoc = associate_scene_exact(tensors, cfg, k_max=k_max)
        else:
            # shape buckets: heterogeneous scenes (ScanNet frame counts and
            # cloud sizes vary per scan) land on a handful of padded shapes, so
            # the jit caches — and the persistent compilation cache — hit
            # across scenes. scene_pads IS the classifier the retrace
            # family's compile-surface census enumerates with
            from maskclustering_tpu.utils.compile_cache import (
                record_shape_bucket,
                scene_pads,
            )

            f_pad, n_pad = scene_pads(cfg, tensors.num_frames, n_real)
            tensors = pad_scene_tensors(tensors, f_pad, n_pad)

            record_shape_bucket("scene", k_max, f_pad, n_pad)
            sp.set(f_pad=f_pad, n_pad=n_pad)
            assoc = associate_scene_tensors(tensors, cfg, k_max=k_max)
            sp.sync(assoc.mask_valid)
    timings["associate"] = sp.duration

    with tracer.span("graph", scene=seq_name) as sp:
        # host sync 1/1: the compact mask table's M_pad bucket is
        # data-dependent, so the valid table must materialize before the
        # graph program can be dispatched. A wedged chip stalls exactly
        # here (the drain never completes) — the pull is an injection
        # seam, and its stall bound is the DEVICE-PHASE watchdog the
        # scene executors arm around run_scene_device (nesting a second
        # same-budget deadline here would double-count every stall)
        faults.inject("pull", seq_name)
        with sanctioned_pull("mask_valid"):
            mask_valid_host = np.asarray(assoc.mask_valid)
        obs.count("pipeline.host_sync")
        sp.set(host_pull="mask_valid")
        table = build_mask_table(mask_valid_host, pad_multiple=cfg.mask_pad_multiple)
        sp.set(m_pad=table.m_pad)
        stats = compute_graph_stats(
            assoc.mask_of_point,
            assoc.boundary,
            jnp.asarray(table.frame),
            jnp.asarray(table.mask_id),
            jnp.asarray(table.valid),
            k_max=k_max,
            point_chunk=cfg.point_chunk,
            mask_visible_threshold=cfg.mask_visible_threshold,
            contained_threshold=cfg.contained_threshold,
            undersegment_filter_threshold=cfg.undersegment_filter_threshold,
            big_mask_point_count=cfg.big_mask_point_count,
            count_dtype=cfg.count_dtype,
        )
        # the schedule stays on device (f32 exact-integer-rank formulation,
        # shared with the fused mesh path): graph -> schedule -> clustering
        # is one uninterrupted dispatch chain, no 20-float round-trip
        schedule = _observer_schedule_jit(stats.observer_hist,
                                          max_len=cfg.max_cluster_iterations)
        sp.sync((stats, schedule))
    timings["graph"] = sp.duration

    with tracer.span("cluster", scene=seq_name) as sp:
        active = jnp.asarray(table.valid) & ~stats.undersegment
        result = iterative_clustering(
            stats.visible, stats.contained, active, schedule,
            view_consensus_threshold=cfg.view_consensus_threshold,
            count_dtype=cfg.count_dtype,
        )
        # NO host sync here anymore: the assignment stays device-resident
        # (the post-process's routing prep runs on device, and the host
        # copy for reporting rides the post-process's final drain). The
        # armed-obs fence below is timing attribution only.
        assignment = sp.sync(result.assignment)
    timings["cluster"] = sp.duration

    return DeviceHandoff(
        table=table, assignment=assignment, active=active,
        node_visible=result.node_visible, first_id=assoc.first_id,
        last_id=assoc.last_id, scene_points=np.asarray(tensors.scene_points),
        frame_ids=tensors.frame_ids, k_max=k_max, n_real=n_real,
        seq_name=seq_name, timings=timings)


def run_scene_host(handoff: DeviceHandoff, cfg: PipelineConfig, *,
                   export: bool = False, object_dict_dir: Optional[str] = None,
                   prediction_root: str = "data/prediction") -> SceneResult:
    """Host phase of one scene: post-process + artifact export.

    Safe to run on a worker thread concurrently with the NEXT scene's
    device phase (jax dispatch is thread-safe; the claim kernels here
    interleave with the next scene's stage programs on the device queue,
    while DBSCAN/merge/export are pure host work). Consumes the handoff's
    device arrays — they are released when this returns.
    """
    timings = dict(handoff.timings)
    tracer = obs.scene_tracer()
    seq_name = handoff.seq_name
    # fault seam: the host tail (claims drain, DBSCAN, merge)
    faults.inject("host", seq_name)

    with tracer.span("postprocess", scene=seq_name) as sp:
        post_timings: Dict[str, float] = {}
        from maskclustering_tpu.models.postprocess_device import run_postprocess
        from maskclustering_tpu.obs import digest as sentinel

        # sentinel: dispatch the invariant-digest program FIRST — it reads
        # the handoff planes before any post-process kernel could donate
        # them; its tiny uint32 output is pulled at the drain tail below
        digest_dev = sentinel.digest_scene_device(handoff)

        objects = run_postprocess(
            cfg, handoff.scene_points, handoff.first_id, handoff.last_id,
            handoff.table.frame, handoff.table.mask_id, handoff.active,
            handoff.assignment, handoff.node_visible, handoff.frame_ids,
            k_max=handoff.k_max, timings=post_timings, n_real=handoff.n_real,
            seq_name=seq_name)
        # the report/SceneResult copy of the assignment rides the tail of
        # the post-process drain: every device kernel has retired by now,
        # so this O(M_pad) pull costs one small DMA, not a pipeline stall
        with obs.span("post.assignment.pull"):
            assignment = np.asarray(handoff.assignment)
        obs.count_transfer("d2h", assignment.nbytes, "post.drain")
        # sentinel: the digest vector rides the same retired drain — one
        # more O(1) DMA on the emit-only tail, zero new pipeline.host_sync
        with obs.span("post.digest.pull"):
            digest_vec = np.asarray(digest_dev)
        obs.count_transfer("d2h", digest_vec.nbytes, "post.drain")
    timings["postprocess"] = sp.duration
    for k, v in post_timings.items():
        # phase wall times measured by the postprocess _PhaseTimer become
        # child spans of "postprocess": same event schema, no double-timing
        obs.record_span(f"post.{k}", v, parent="postprocess")
        timings[f"post.{k}"] = v

    if export:
        if seq_name is None or object_dict_dir is None:
            raise ValueError("export=True requires seq_name and object_dict_dir")
        # fault seam: artifact export (atomic tmp+rename, so an injected
        # failure here can never leave a truncated npz for resume to latch)
        faults.inject("export", seq_name)
        export_artifacts(objects, seq_name, cfg.config_name, object_dict_dir,
                         prediction_root=prediction_root,
                         top_k_repre=cfg.num_representative_masks)

    # fault seam: "corrupt" silently bit-flips the pulled graph stat — it
    # deliberately does NOT raise, so the retry/degradation ladder never
    # heals it; only the digest comparison downstream can catch it
    if faults.take_corruption("host", seq_name):
        assignment = assignment.copy()
        assignment[0] ^= 0x1

    digest = sentinel.compose_scene_digest(
        digest_vec, handoff, assignment, objects,
        count_dtype=cfg.count_dtype)

    log.info("scene %s: %d objects, timings %s", seq_name, len(objects.point_ids_list),
             {k: round(v, 3) for k, v in timings.items()})
    return SceneResult(objects=objects, table=handoff.table,
                       assignment=assignment, timings=timings,
                       digest=digest)


def run_scene(tensors: SceneTensors, cfg: PipelineConfig, *, k_max: Optional[int] = None,
              seq_name: Optional[str] = None, export: bool = False,
              object_dict_dir: Optional[str] = None,
              prediction_root: str = "data/prediction") -> SceneResult:
    """Cluster one scene. Returns objects + artifacts (optionally written).

    The sequential composition of the device and host phases — what the
    overlapped executor (run.py) pipelines across scenes. Identical
    results either way (pinned by tests/test_executor.py); the ``timings``
    keys are unchanged from the pre-split pipeline.
    """
    handoff = run_scene_device(tensors, cfg, k_max=k_max, seq_name=seq_name)
    return run_scene_host(handoff, cfg, export=export,
                          object_dict_dir=object_dict_dir,
                          prediction_root=prediction_root)
