"""Per-scene end-to-end pipeline: association -> graph -> clustering -> export.

The TPU analog of the reference's per-scene entry (main.py:9-21). Device
stages run under jit with static, bucket-padded shapes; the two host sync
points are (a) the mask table (compact indices of valid masks) and (b) the
observer schedule (a 20-float transfer), mirroring where the reference
crosses to numpy.
"""

from __future__ import annotations

import logging
from typing import Dict, NamedTuple, Optional

import jax.numpy as jnp
import numpy as np

from maskclustering_tpu import obs
from maskclustering_tpu.config import PipelineConfig
from maskclustering_tpu.datasets.base import SceneTensors
from maskclustering_tpu.models.backprojection import associate_scene_tensors
from maskclustering_tpu.models.clustering import ClusterResult, iterative_clustering
from maskclustering_tpu.models.graph import (
    GraphStats,
    MaskTable,
    build_mask_table,
    compute_graph_stats,
    observer_schedule,
)
from maskclustering_tpu.models.postprocess import SceneObjects, export_artifacts

log = logging.getLogger("maskclustering_tpu")


class SceneResult(NamedTuple):
    objects: SceneObjects
    table: MaskTable
    assignment: np.ndarray
    timings: Dict[str, float]


K_MAX_CEILING = 1023


# canonical home is the compile-cache module (bounding distinct jit shapes
# is its hit rate); re-exported here for the scripts/tests that always
# imported it from the pipeline
from maskclustering_tpu.utils.compile_cache import bucket_size  # noqa: E402


def pad_scene_tensors(tensors: SceneTensors, f_pad: int, n_pad: int) -> SceneTensors:
    """Pad a scene to a (F_pad, N_pad) shape bucket.

    Padded frames are invalid (frame_valid=False -> no claims); padded
    points sit at a far sentinel coordinate no frustum reaches within
    depth_trunc (same invariants as the mesh batch path, parallel/batch.py).
    Frame arrays pad in their current residence: device arrays via jnp (the
    bench renders frames directly in HBM), host numpy via np — host frames
    MUST stay host so the compact-feed codec (io/feed.py) still sees them
    before the upload in associate_scene_tensors.
    """
    import dataclasses

    f, n = tensors.num_frames, tensors.num_points
    if f == f_pad and n == n_pad:
        return tensors
    if f_pad < f or n_pad < n:
        raise ValueError(f"bucket ({f_pad}, {n_pad}) smaller than scene ({f}, {n})")
    pts = np.full((n_pad, 3), 1.0e4, dtype=np.float32)
    pts[:n] = tensors.scene_points
    df = f_pad - f

    def pad_frames(arr, constant_values=0.0):
        widths = ((0, df),) + ((0, 0),) * (np.ndim(arr) - 1)
        if isinstance(arr, jnp.ndarray) and not isinstance(arr, np.ndarray):
            return jnp.pad(arr, widths, constant_values=constant_values)
        return np.pad(np.asarray(arr), widths, constant_values=constant_values)

    return dataclasses.replace(
        tensors,
        scene_points=pts,
        depths=pad_frames(tensors.depths),
        segmentations=pad_frames(tensors.segmentations),
        intrinsics=pad_frames(tensors.intrinsics, constant_values=1.0),
        cam_to_world=pad_frames(tensors.cam_to_world, constant_values=0.0),
        frame_valid=np.concatenate([np.asarray(tensors.frame_valid),
                                    np.zeros(df, dtype=bool)]),
        frame_ids=list(tensors.frame_ids) + [None] * df,
    )


def bucket_k_max(max_id: int, minimum: int = 63, ceiling: int = K_MAX_CEILING) -> int:
    """Smallest (2^b - 1) >= max(max_id, minimum): few jit buckets, no aliasing.

    Clamped at ``ceiling``: one corrupt id in a uint16 id-map (e.g. 65535)
    would otherwise blow up the dense f*k_max slot tables and (M,M) matrices.
    Ids above k_max are dropped as background by associate_frame, so a clamp
    degrades gracefully to ignoring the corrupt masks.
    """
    k = minimum
    while k < max_id and k < ceiling:
        k = k * 2 + 1
    if max_id > k:
        log.warning(
            "segmentation ids up to %d exceed k_max ceiling %d; "
            "masks with larger ids are treated as background", max_id, k)
    return k


def run_scene(tensors: SceneTensors, cfg: PipelineConfig, *, k_max: Optional[int] = None,
              seq_name: Optional[str] = None, export: bool = False,
              object_dict_dir: Optional[str] = None,
              prediction_root: str = "data/prediction") -> SceneResult:
    """Cluster one scene. Returns objects + artifacts (optionally written).

    ``k_max`` (max mask id per frame) defaults to a power-of-two bucket of the
    scene's true max segmentation id, so crowded frames (CropFormer id-maps
    are uint16) are never truncated while jit recompiles stay rare.

    Stage timing comes from obs spans (obs.scene_tracer()): with obs armed
    every stage is sync-fenced at its boundary (``sp.sync``), so device
    work is attributed to the stage that dispatched it instead of the
    stage that first pulls a result; disarmed, the spans are timing-only
    and add no syncs — identical behavior to the legacy perf_counter
    timings. The ``timings`` keys are unchanged either way.
    """
    timings: Dict[str, float] = {}
    tracer = obs.scene_tracer()

    if k_max is None:
        max_id = int(np.max(tensors.segmentations)) if np.size(tensors.segmentations) else 0
        k_max = bucket_k_max(max_id)

    n_real = tensors.num_points
    with tracer.span("associate", scene=seq_name, k_max=k_max,
                     num_frames=tensors.num_frames, num_points=n_real) as sp:
        if cfg.use_exact_ball_query:
            # host-only parity path: no jit shape buckets, padding would only
            # add pointless device round-trips
            from maskclustering_tpu.models.exact_backprojection import associate_scene_exact

            assoc = associate_scene_exact(tensors, cfg, k_max=k_max)
        else:
            # shape buckets: heterogeneous scenes (ScanNet frame counts and
            # cloud sizes vary per scan) land on a handful of padded shapes, so
            # the jit caches — and the persistent compilation cache — hit
            # across scenes
            f_pad = bucket_size(tensors.num_frames, max(cfg.frame_pad_multiple, 1))
            n_pad = bucket_size(n_real, max(cfg.point_chunk, 1))
            tensors = pad_scene_tensors(tensors, f_pad, n_pad)
            from maskclustering_tpu.utils.compile_cache import record_shape_bucket

            record_shape_bucket("scene", k_max, f_pad, n_pad)
            sp.set(f_pad=f_pad, n_pad=n_pad)
            assoc = associate_scene_tensors(tensors, cfg, k_max=k_max)
            sp.sync(assoc.mask_valid)
        mask_valid_host = np.asarray(assoc.mask_valid)
    timings["associate"] = sp.duration

    with tracer.span("graph", scene=seq_name) as sp:
        table = build_mask_table(mask_valid_host, pad_multiple=cfg.mask_pad_multiple)
        sp.set(m_pad=table.m_pad)
        stats = compute_graph_stats(
            assoc.mask_of_point,
            assoc.boundary,
            jnp.asarray(table.frame),
            jnp.asarray(table.mask_id),
            jnp.asarray(table.valid),
            k_max=k_max,
            point_chunk=cfg.point_chunk,
            mask_visible_threshold=cfg.mask_visible_threshold,
            contained_threshold=cfg.contained_threshold,
            undersegment_filter_threshold=cfg.undersegment_filter_threshold,
            big_mask_point_count=cfg.big_mask_point_count,
        )
        schedule = observer_schedule(stats.observer_hist,
                                     max_len=cfg.max_cluster_iterations)
        sp.sync(stats)
    timings["graph"] = sp.duration

    with tracer.span("cluster", scene=seq_name) as sp:
        active = jnp.asarray(table.valid) & ~stats.undersegment
        result = iterative_clustering(
            stats.visible, stats.contained, active, jnp.asarray(schedule),
            view_consensus_threshold=cfg.view_consensus_threshold,
        )
        assignment = np.asarray(sp.sync(result.assignment))
        obs.count_transfer("d2h", assignment.nbytes, "cluster")
    timings["cluster"] = sp.duration

    with tracer.span("postprocess", scene=seq_name) as sp:
        post_timings: Dict[str, float] = {}
        from maskclustering_tpu.models.postprocess_device import run_postprocess

        objects = run_postprocess(
            cfg, tensors.scene_points, assoc.first_id, assoc.last_id,
            table.frame, table.mask_id, active, assignment, result.node_visible,
            tensors.frame_ids, k_max=k_max, timings=post_timings, n_real=n_real)
    timings["postprocess"] = sp.duration
    for k, v in post_timings.items():
        # phase wall times measured by the postprocess _PhaseTimer become
        # child spans of "postprocess": same event schema, no double-timing
        obs.record_span(f"post.{k}", v, parent="postprocess")
        timings[f"post.{k}"] = v

    if export:
        if seq_name is None or object_dict_dir is None:
            raise ValueError("export=True requires seq_name and object_dict_dir")
        export_artifacts(objects, seq_name, cfg.config_name, object_dict_dir,
                         prediction_root=prediction_root,
                         top_k_repre=cfg.num_representative_masks)

    log.info("scene %s: %d objects, timings %s", seq_name, len(objects.point_ids_list),
             {k: round(v, 3) for k, v in timings.items()})
    return SceneResult(objects=objects, table=table, assignment=assignment, timings=timings)
