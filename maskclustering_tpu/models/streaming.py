"""Streaming incremental clustering: chunked frame accumulation.

The batch pipeline (models/pipeline.py) keeps every frame's (F, N) claim
planes resident until one graph solve — full-plane residency is the scale
ceiling on frames, and a live scanner gets nothing until the scan ends.
This module processes frames in chunks of ``cfg.streaming_chunk`` and
maintains a device-resident accumulator whose footprint is one chunk's
(F', N) planes plus O(M^2) graph state:

- **within-chunk statistics are exact**: each chunk runs the batch
  ``compute_graph_stats`` program over its own claim planes and mask
  table — the same counting contractions (ops/counting.py), which are
  additive over frame chunks;
- **cross-chunk statistics run at representative granularity**: past
  chunks survive as the point-level ``rep_plane`` (point -> current
  cluster representative, the SAM3D-style progressive instance map) and
  the accumulated visibility/containment matrices. A new chunk's merge
  program computes, with ONE counting matmul per point chunk, how every
  existing representative projects into the new frames (the
  view-consensus analog of SAM3D's progressive mask merging);
- **periodic re-cluster warm-starts from the previous assignment**:
  connected components under the observer schedule restart from the
  prior labels (``iterative_clustering(init=...)``), not singletons;
- **anytime partial instances**: after every chunk the rep plane yields
  the current instance map; the chunk digest carries the live instance
  count and ``partial_objects()`` exports a full partial artifact set.

Convergence contract (tests/test_streaming.py): when one chunk covers
the whole scene the accumulator degenerates to the batch program chain —
artifacts are BYTE-IDENTICAL under both ``count_dtype`` encodings — and
at smaller chunks the final AP matches the batch path within the pinned
tolerance on the solvable synthetic scene.

Compile surface: chunks route through the same
``utils/compile_cache.scene_bucket`` vocabulary as whole scenes (a chunk
is just another bucket coordinate), every chunk pads to the SAME
(f_chunk_pad, n_pad) bucket (partial last chunks included), and the
global mask axis is pre-sized from the first chunk's density
(``cfg.stream_mask_headroom``) — so chunk 1 compiles the stream's
programs once and chunks 2..K dispatch with zero compiles (the retrace
sanitizer pins it; the streaming jits are classified in
analysis/retrace.SERVING_PROGRAMS).

Residency contract: ``stream.max_plane_bytes`` (gauge_max) records the
largest per-chunk claim-plane materialization — strictly under the full
scene's plane set at any multi-chunk split — and ``stream.state_bytes``
the accumulator itself. Host syncs are booked on ``stream.host_sync``
(two per chunk: the irreducible mask-table pull + the partial-instance
scalar), marked with ``sanctioned_pull`` windows like the batch path's.
"""

from __future__ import annotations

import dataclasses
import functools
import logging
import math
import os
import time
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from maskclustering_tpu import obs
from maskclustering_tpu.analysis.lock_sanitizer import mct_lock
from maskclustering_tpu.analysis.transfer_guard import sanctioned_pull
from maskclustering_tpu.config import PipelineConfig
from maskclustering_tpu.datasets.base import SceneTensors
from maskclustering_tpu.models.backprojection import associate_scene_tensors
from maskclustering_tpu.models.clustering import iterative_clustering
from maskclustering_tpu.models.graph import (
    MaskTable,
    build_mask_table,
    compute_graph_stats,
    frame_segment_stats,
    observer_histogram,
    observer_schedule_device,
)
from maskclustering_tpu.models.pipeline import (
    DeviceHandoff,
    SceneResult,
    bucket_k_max,
    pad_scene_tensors,
)
from maskclustering_tpu.models.postprocess import (
    SceneObjects,
    _merge_overlapping,
    export_artifacts,
)
from maskclustering_tpu.ops import counting
from maskclustering_tpu.ops.dbscan import dbscan_labels_parallel
from maskclustering_tpu.utils import faults
from maskclustering_tpu.utils.compile_cache import (
    record_shape_bucket,
    scene_pads,
)

log = logging.getLogger("maskclustering_tpu")

# streaming accumulator state-journal schema (resume compatibility gate)
STREAM_STATE_VERSION = 1


class StaleChunkAttempt(RuntimeError):
    """A watchdog-abandoned push_chunk attempt reached its bind point
    after a retry superseded it; the bind was dropped (the accumulator is
    the RETRY's state). Raised on the abandoned daemon thread only —
    callers on the live path never see it."""

    def __init__(self, seq_name, chunk: int):
        super().__init__(f"stream {seq_name}: abandoned chunk {chunk} "
                         f"attempt superseded; bind dropped")


def slice_scene_frames(tensors: SceneTensors, start: int,
                       stop: int) -> SceneTensors:
    """The frame window [start, stop) of a scene as its own SceneTensors.

    The cloud is shared (same object); frame arrays slice along axis 0.
    Host numpy stays host (the compact-feed codec contract,
    models/pipeline.pad_scene_tensors).
    """
    return dataclasses.replace(
        tensors,
        depths=tensors.depths[start:stop],
        segmentations=tensors.segmentations[start:stop],
        intrinsics=tensors.intrinsics[start:stop],
        cam_to_world=tensors.cam_to_world[start:stop],
        frame_valid=np.asarray(tensors.frame_valid)[start:stop],  # mct-ok: AST.HOSTSYNC (host numpy by SceneTensors contract, no device sync)
        frame_ids=list(tensors.frame_ids)[start:stop],
    )


# ---------------------------------------------------------------------------
# the three streaming device programs
# ---------------------------------------------------------------------------


@functools.partial(
    jax.jit,
    static_argnames=("k_max", "point_chunk", "mask_visible_threshold",
                     "contained_threshold", "big_mask_point_count",
                     "count_dtype"),
)
def _stream_merge_impl(
    visible_acc: jnp.ndarray,  # (M, F_alloc) bool accumulated visibility
    contained_acc: jnp.ndarray,  # (M, M) bool accumulated containment
    active_acc: jnp.ndarray,  # (M,) bool
    n_tot_acc: jnp.ndarray,  # (M,) f32
    assignment: jnp.ndarray,  # (M,) int32 current assignment
    rep_plane: jnp.ndarray,  # (N,) int32 point -> rep slot + 1 (0 = none)
    mask_of_point: jnp.ndarray,  # (Fc, N) int32 chunk claim planes
    vis_k: jnp.ndarray,  # (Mk, Fc) bool chunk-local visible (post-undo)
    con_k: jnp.ndarray,  # (Mk, Mk) bool chunk-local contained
    act_k: jnp.ndarray,  # (Mk,) bool chunk-local active
    ntot_k: jnp.ndarray,  # (Mk,) f32
    chunk_frame: jnp.ndarray,  # (Mk,) int32 local frame per chunk mask
    chunk_id: jnp.ndarray,  # (Mk,) int32 (-1 padding)
    slot_offset: jnp.ndarray,  # () int32 global slot of chunk mask 0
    frames_base: jnp.ndarray,  # () int32 column base of this chunk
    *,
    k_max: int,
    point_chunk: int,
    mask_visible_threshold: float,
    contained_threshold: float,
    big_mask_point_count: int,
    count_dtype: str,
):
    """Fold one chunk into the accumulator: exact within-chunk blocks +
    rep-level cross terms, all via the additive counting contractions.

    ``c_cross[r, m'] = #points of representative r claimed by chunk mask
    m'`` is the same chunked ``count_dot`` the batch co-occurrence uses
    (models/graph._cooccurrence), with the representative membership
    one-hot (from ``rep_plane``) standing in for the frame claim rows —
    summing these per-chunk contractions over the stream IS the additive
    decomposition the counting accumulators make exact.
    """
    m_pad = visible_acc.shape[0]
    fc, n = mask_of_point.shape
    arange_m = jnp.arange(m_pad, dtype=jnp.int32)
    # prior representatives: active fixpoints of the current assignment
    # (new slots are not active yet, so chunk 1 has none)
    is_rep = active_acc & (assignment == arange_m)

    # ---- c_cross via chunked counting matmuls ----
    n_chunks = max(1, -(-n // point_chunk))
    n_padded = n_chunks * point_chunk
    mop = jnp.pad(mask_of_point, ((0, 0), (0, n_padded - n)))
    rp = jnp.pad(rep_plane, (0, n_padded - n))
    safe_frame = jnp.minimum(chunk_frame, fc - 1)
    acc_dtype = counting.accumulator_dtype(count_dtype)
    mk = chunk_frame.shape[0]

    def body(carry, start):
        c_acc, npts_acc = carry
        mc = jax.lax.dynamic_slice(mop, (0, start), (fc, point_chunk))
        rc = jax.lax.dynamic_slice(rp, (start,), (point_chunk,))
        ids = mc[safe_frame, :].T  # (Nc, Mk)
        w = (ids == chunk_id[None, :])
        a = (rc[:, None] == (arange_m[None, :] + 1))  # (Nc, M) rep membership
        cw = counting.count_dot(a.T, w, count_dtype=count_dtype,
                                out_dtype=None)
        return (c_acc + cw,
                npts_acc + jnp.sum(a, axis=0).astype(jnp.float32)), None

    init = (jnp.zeros((m_pad, mk), acc_dtype), jnp.zeros((m_pad,), jnp.float32))
    (c_cross, rep_npts), _ = jax.lax.scan(
        body, init, jnp.arange(n_chunks) * point_chunk)
    c_cross = c_cross.astype(jnp.float32)

    # ---- per-frame segmented max/sum over the chunk's mask columns ----
    # (chunk masks are (frame, id)-sorted — the ONE shared batch
    # formulation, models/graph.frame_segment_stats)
    cmax, top_local, n_vis = frame_segment_stats(c_cross, chunk_frame, fc,
                                                 k_max)  # (M, Fc) x3

    # ---- representative visibility/containment in the new frames ----
    # (the batch visibility test, models/graph.py, with the rep's point
    # count as n_tot; reps never re-enter the undersegment logic)
    safe_tot = jnp.maximum(rep_npts, 1.0)[:, None]
    vis_ratio = n_vis / safe_tot
    visible_test = ((vis_ratio >= mask_visible_threshold)
                    | (n_vis >= big_mask_point_count)) \
        & (n_vis > 0) & is_rep[:, None]
    passes = (cmax / jnp.maximum(n_vis, 1.0)) > contained_threshold
    vis_cross = visible_test & passes  # (M, Fc)

    rows = jnp.broadcast_to(arange_m[:, None], (m_pad, fc))
    safe_top = jnp.where(vis_cross, slot_offset + top_local, m_pad)
    contained_new = jnp.zeros((m_pad, m_pad), dtype=bool)
    contained_new = contained_new.at[
        rows.reshape(-1), safe_top.reshape(-1)].set(True, mode="drop")

    # ---- fold the chunk blocks into the accumulator ----
    vis_cols = jax.lax.dynamic_update_slice(vis_cross, vis_k,
                                            (slot_offset, jnp.int32(0)))
    visible_acc = jax.lax.dynamic_update_slice(
        visible_acc, vis_cols, (jnp.int32(0), frames_base))
    con_block = jnp.zeros((m_pad, m_pad), dtype=bool)
    con_block = jax.lax.dynamic_update_slice(
        con_block, con_k, (slot_offset, slot_offset))
    contained_acc = contained_acc | con_block | contained_new
    active_acc = jax.lax.dynamic_update_slice(active_acc, act_k,
                                              (slot_offset,))
    n_tot_acc = jax.lax.dynamic_update_slice(n_tot_acc, ntot_k,
                                             (slot_offset,))
    return visible_acc, contained_acc, active_acc, n_tot_acc


@functools.partial(
    jax.jit,
    static_argnames=("max_len", "view_consensus_threshold", "count_dtype"),
)
def _stream_recluster_impl(
    visible_acc: jnp.ndarray,  # (M, F_alloc) bool
    contained_acc: jnp.ndarray,  # (M, M) bool
    active_acc: jnp.ndarray,  # (M,) bool
    prev_assign: jnp.ndarray,  # (M,) int32 warm-start labels
    *,
    max_len: int,
    view_consensus_threshold: float,
    count_dtype: str,
):
    """Periodic re-cluster over the accumulated state.

    The observer-percentile schedule recomputes from the accumulated
    visibility exactly as the batch graph stage does (shared
    ``observer_histogram`` / ``observer_schedule_device`` formulations),
    then the iterative merge restarts from the PREVIOUS assignment — new
    chunk masks enter as singletons, existing clusters as themselves, so
    the solve costs the iterations to absorb the new chunk rather than a
    from-scratch component search.
    """
    observers = counting.count_dot(visible_acc, visible_acc.T,
                                   count_dtype=count_dtype)
    hist = observer_histogram(observers, visible_acc.shape[1] + 1)
    schedule = observer_schedule_device(hist, max_len=max_len)
    return iterative_clustering(
        visible_acc, contained_acc, active_acc, schedule, prev_assign,
        view_consensus_threshold=view_consensus_threshold,
        count_dtype=count_dtype)


@functools.partial(
    jax.jit, static_argnames=("chunk_frames", "min_points"))
def _rep_plane_update_impl(
    rep_plane: jnp.ndarray,  # (N,) int32 point -> rep slot + 1
    rep_votes: jnp.ndarray,  # (N,) int32 supporting-claim count
    first_id: jnp.ndarray,  # (Fc, N) int16 chunk claim planes
    last_id: jnp.ndarray,  # (Fc, N) int16
    slot_of: jnp.ndarray,  # (Fc, k_max + 2) int32 (frame, id) -> slot, -1 none
    assignment: jnp.ndarray,  # (M,) int32
    *,
    chunk_frames: int,  # candidate claim rows to read (<= Fc)
    min_points: int,  # liveness floor of the partial-instance count
):
    """Streaming majority vote: fold one chunk's claims into the point ->
    representative plane.

    Candidates per point: its prior representative (weighted by the
    accumulated supporting-claim count, listed FIRST so ties keep the
    prior) plus the chunk's first/last claims mapped through the current
    assignment (``last`` deduped against ``first`` exactly like the batch
    claims COO, models/postprocess._claims_coo). The winner is the
    candidate with the most supporting claims — a per-point streaming
    mode estimate whose weight is total evidence, so a long-standing
    assignment is not flipped by one noisy frame.
    """
    m = assignment.shape[0]
    # points follow their representative through merges first
    prior_slot = jnp.maximum(rep_plane - 1, 0)
    prior = jnp.where(rep_plane > 0, assignment[prior_slot] + 1, 0)

    first = first_id[:chunk_frames].astype(jnp.int32)
    last = last_id[:chunk_frames].astype(jnp.int32)
    last = jnp.where(last == first, 0, last)  # each claim counts once

    def rep_of(ids, f):
        slot = slot_of[f, jnp.clip(ids, 0, slot_of.shape[1] - 1)]
        rep = jnp.where(slot >= 0,
                        assignment[jnp.clip(slot, 0, m - 1)] + 1, 0)
        return jnp.where(ids > 0, rep, 0)

    cand = jnp.stack(
        [prior]
        + [rep_of(first[f], f) for f in range(chunk_frames)]
        + [rep_of(last[f], f) for f in range(chunk_frames)], axis=0)
    c_rows = cand.shape[0]
    weights = jnp.concatenate(
        [jnp.maximum(rep_votes, 1)[None, :],
         jnp.ones((c_rows - 1, cand.shape[1]), jnp.int32)], axis=0)

    def tally(votes, j):
        eq = (cand == cand[j][None, :]) & (cand > 0)
        return votes + eq.astype(jnp.int32) * weights[j][None, :], None

    votes, _ = jax.lax.scan(
        tally, jnp.zeros(cand.shape, jnp.int32),
        jnp.arange(c_rows))
    winner = jnp.argmax(votes, axis=0)  # first max wins: prior row is first
    new_rep = jnp.take_along_axis(cand, winner[None, :], axis=0)[0]
    new_votes = jnp.max(votes, axis=0)

    sizes = jnp.zeros(m + 1, jnp.int32).at[
        jnp.clip(new_rep, 0, m)].add(1)
    partial = jnp.sum(sizes[1:] >= min_points).astype(jnp.int32)
    return new_rep, new_votes, partial


# ---------------------------------------------------------------------------
# the accumulator
# ---------------------------------------------------------------------------


class StreamAccumulator:
    """Device-resident streaming state for one scene's chunked stream.

    ``push_chunk`` is transactional: all device programs run against the
    CURRENT state and the new state binds only after every program
    dispatched — so a mid-chunk fault leaves the accumulator exactly at
    the previous chunk's fixpoint and the chunk retries cleanly (the
    ``chunk`` fault seam + tests/test_streaming.py pin it). The bind is
    additionally EPOCH-FENCED: a watchdog-abandoned push_chunk keeps
    running on its daemon thread (``faults.call_with_deadline``
    semantics) and could otherwise bind its chunk AFTER the retry
    re-ran it — every push_chunk entry invalidates all older in-flight
    attempts, so a stale attempt's bind raises instead of
    double-accumulating (run.py's chunk retry and the serve path's
    client resend both ride this fence).
    """

    def __init__(self, cfg: PipelineConfig, *, total_frames: int,
                 num_points: int, k_max: Optional[int] = None,
                 seq_name: Optional[str] = None):
        if cfg.streaming_chunk <= 0:
            raise ValueError("StreamAccumulator needs cfg.streaming_chunk > 0")
        self.cfg = cfg
        self.seq_name = seq_name
        self.total_frames = int(total_frames)
        self.chunk_frames = min(int(cfg.streaming_chunk), self.total_frames)
        self.n_chunks = max(-(-self.total_frames // self.chunk_frames), 1)
        self.single = self.n_chunks == 1
        f_pad_full, self.n_pad = scene_pads(cfg, self.total_frames,
                                            num_points)
        # every chunk (partial last one included) pads to ONE bucket so
        # chunks 2..K dispatch the exact programs chunk 1 compiled
        self.f_chunk_pad = (f_pad_full if self.single
                            else scene_pads(cfg, self.chunk_frames,
                                            num_points)[0])
        self.f_alloc = self.n_chunks * self.f_chunk_pad
        self.n_real = int(num_points)
        self.k_max = int(k_max) if k_max else 0
        # host-side global mask table (grows by chunk, (frame, id)-sorted
        # because frames arrive in order and chunks append)
        self.m_pad = 0
        self.masks_used = 0
        self.g_frame: Optional[np.ndarray] = None
        self.g_mask_id: Optional[np.ndarray] = None
        self.frame_ids: List = []
        self.chunks_done = 0
        self.frames_done = 0
        self.partial_instances = 0
        self.timings: Dict[str, float] = {}
        # device state (allocated at the first chunk, once m_pad is sized)
        self.visible = None
        self.contained = None
        self.active = None
        self.n_tot = None
        self.assignment = None
        self.node_visible = None
        self.rep_plane = None
        self.rep_votes = None
        self.scene_points: Optional[np.ndarray] = None
        # single-chunk streams keep the chunk's planes for the exact
        # batch post-process (the byte-identity path)
        self._single_assoc = None
        self._single_points = None
        self._single_frame_ids = None
        self._single_table: Optional[MaskTable] = None
        # the abandoned-attempt fence (see class docstring): entry bumps
        # the epoch, the bind re-checks it under the lock
        self._epoch = 0
        self._bind_lock = mct_lock("streaming.StreamAccumulator._bind_lock")

    # -- sizing -------------------------------------------------------------

    def _presize_m_pad(self, chunk_table: MaskTable) -> int:
        """Global mask-axis bucket: exact for single-chunk streams (the
        batch m_pad, so the post-process shapes match bit-for-bit),
        projected from the first chunk's density otherwise."""
        from maskclustering_tpu.utils.compile_cache import bucket_size

        if self.single:
            return chunk_table.m_pad
        projected = int(math.ceil(
            max(chunk_table.num_masks, 1) * self.n_chunks
            * self.cfg.stream_mask_headroom))
        return max(bucket_size(projected, self.cfg.mask_pad_multiple),
                   chunk_table.m_pad)

    def _alloc_state(self, m_pad: int) -> None:
        # host-built zeros device_put in (jnp.asarray): eager jnp.zeros/
        # arange dispatch tiny broadcast_in_dim/iota programs per
        # allocation, which the retrace sanitizer would book as repeat
        # compiles on every new stream — device_put compiles nothing
        self.m_pad = m_pad
        self.g_frame = np.full(m_pad, self.total_frames, dtype=np.int32)
        self.g_mask_id = np.full(m_pad, -1, dtype=np.int32)
        self.visible = jnp.asarray(
            np.zeros((m_pad, self.f_alloc), dtype=bool))
        self.contained = jnp.asarray(np.zeros((m_pad, m_pad), dtype=bool))
        self.active = jnp.asarray(np.zeros((m_pad,), dtype=bool))
        self.n_tot = jnp.asarray(np.zeros((m_pad,), np.float32))
        self.assignment = jnp.asarray(np.arange(m_pad, dtype=np.int32))
        self.node_visible = jnp.asarray(
            np.zeros((m_pad, self.f_alloc), dtype=bool))
        self.rep_plane = jnp.asarray(np.zeros((self.n_pad,), np.int32))
        self.rep_votes = jnp.asarray(np.zeros((self.n_pad,), np.int32))

    def _grow_state(self, needed: int) -> None:
        """Mask-capacity overflow: grow the bucket (a counted recompile —
        the projection headroom exists to make this rare), never drop."""
        from maskclustering_tpu.utils.compile_cache import bucket_size

        new_pad = bucket_size(needed, self.cfg.mask_pad_multiple)
        log.warning("stream %s: mask capacity %d -> %d (projection "
                    "overflow; chunk programs recompile at the new bucket)",
                    self.seq_name, self.m_pad, new_pad)
        obs.count("stream.mask_capacity_growths")
        dm = new_pad - self.m_pad
        self.g_frame = np.concatenate(
            [self.g_frame, np.full(dm, self.total_frames, np.int32)])
        self.g_mask_id = np.concatenate(
            [self.g_mask_id, np.full(dm, -1, np.int32)])

        # growth IS a pull seam: the accumulator round-trips host once to
        # re-pad (rare by construction; device_put back compiles nothing)
        with sanctioned_pull("stream.capacity_growth"):
            self.visible = jnp.asarray(
                np.pad(np.asarray(self.visible), ((0, dm), (0, 0))))
            self.contained = jnp.asarray(
                np.pad(np.asarray(self.contained), ((0, dm), (0, dm))))
            self.active = jnp.asarray(
                np.pad(np.asarray(self.active), (0, dm)))
            self.n_tot = jnp.asarray(
                np.pad(np.asarray(self.n_tot), (0, dm)))
            self.assignment = jnp.asarray(np.concatenate(
                [np.asarray(self.assignment),
                 np.arange(self.m_pad, new_pad, dtype=np.int32)]))
            self.node_visible = jnp.asarray(
                np.pad(np.asarray(self.node_visible), ((0, dm), (0, 0))))
        self.m_pad = new_pad

    # -- per-chunk update ---------------------------------------------------

    def _bind_state(self, visible, contained, active, n_tot, assignment,
                    node_visible, rep_plane, rep_votes, table_k, offset,
                    num_k, chunk_tensors, real_frames, partial) -> None:
        """The transaction body (caller holds ``_bind_lock`` and has
        verified the attempt's epoch): pure attribute/array assignments,
        no locks, no IO."""
        self.visible, self.contained = visible, contained
        self.active, self.n_tot = active, n_tot
        self.assignment, self.node_visible = assignment, node_visible
        self.rep_plane, self.rep_votes = rep_plane, rep_votes
        self.g_frame[offset:offset + num_k] = (
            self.frames_done + table_k.frame[:num_k])
        self.g_mask_id[offset:offset + num_k] = table_k.mask_id[:num_k]
        self.masks_used = offset + num_k
        self.frame_ids.extend(list(chunk_tensors.frame_ids)[:real_frames])
        self.frames_done += real_frames
        self.chunks_done += 1
        self.partial_instances = partial

    def push_chunk(self, chunk_tensors: SceneTensors) -> Dict:
        """Accumulate one frame chunk; returns the chunk digest."""
        cfg = self.cfg
        t0 = time.perf_counter()
        with self._bind_lock:
            # every new attempt supersedes all in-flight older ones: a
            # watchdog-abandoned thread that later reaches its bind point
            # finds a stale epoch and aborts instead of double-binding
            self._epoch += 1
            epoch = self._epoch
        ci = self.chunks_done
        # fault seam: deterministic injection point for one chunk (a
        # scripted fault here retries the CHUNK, accumulator intact)
        faults.inject("chunk", self.seq_name)
        real_frames = chunk_tensors.num_frames
        with obs.span("stream.chunk", scene=self.seq_name, chunk=ci,
                      frames=real_frames) as sp:
            if self.k_max <= 0:
                from maskclustering_tpu.utils.compile_cache import max_seg_id

                self.k_max = bucket_k_max(max_seg_id(
                    chunk_tensors.segmentations))
            padded = pad_scene_tensors(chunk_tensors, self.f_chunk_pad,
                                       self.n_pad)
            # one bucket vocabulary with the batch path: a chunk is just
            # another scene-bucket coordinate
            record_shape_bucket("scene", self.k_max, self.f_chunk_pad,
                                self.n_pad)
            assoc = associate_scene_tensors(padded, cfg, k_max=self.k_max)
            plane_bytes = (assoc.mask_of_point.nbytes + assoc.first_id.nbytes
                           + assoc.last_id.nbytes + assoc.point_visible.nbytes
                           + assoc.boundary.nbytes)
            obs.gauge_max("stream.max_plane_bytes", float(plane_bytes))

            # the irreducible pull: the chunk mask table's bucket is
            # data-dependent (the batch path's one host sync, per chunk)
            faults.inject("pull", self.seq_name)
            with sanctioned_pull("stream.mask_valid"):
                mask_valid_host = np.asarray(assoc.mask_valid)
            obs.count("stream.host_sync")
            table_k = build_mask_table(mask_valid_host,
                                       pad_multiple=cfg.mask_pad_multiple)
            sp.set(m_pad=table_k.m_pad, plane_bytes=plane_bytes)

            if self.chunks_done == 0:
                self._alloc_state(self._presize_m_pad(table_k))
                record_shape_bucket("stream", self.m_pad, self.f_alloc,
                                    self.n_pad)
                self.scene_points = np.asarray(chunk_tensors.scene_points)  # mct-ok: AST.HOSTSYNC (host numpy by SceneTensors contract)
            elif self.masks_used + table_k.m_pad > self.m_pad:
                self._grow_state(self.masks_used + table_k.m_pad)
                record_shape_bucket("stream", self.m_pad, self.f_alloc,
                                    self.n_pad)

            offset = self.masks_used
            num_k = table_k.num_masks

            # exact within-chunk graph statistics (the batch program)
            stats = compute_graph_stats(
                assoc.mask_of_point, assoc.boundary,
                jnp.asarray(table_k.frame), jnp.asarray(table_k.mask_id),
                jnp.asarray(table_k.valid),
                k_max=self.k_max, point_chunk=cfg.point_chunk,
                mask_visible_threshold=cfg.mask_visible_threshold,
                contained_threshold=cfg.contained_threshold,
                undersegment_filter_threshold=cfg.undersegment_filter_threshold,
                big_mask_point_count=cfg.big_mask_point_count,
                count_dtype=cfg.count_dtype)
            act_k = jnp.asarray(table_k.valid) & ~stats.undersegment

            visible, contained, active, n_tot = _stream_merge_impl(
                self.visible, self.contained, self.active, self.n_tot,
                self.assignment, self.rep_plane,
                assoc.mask_of_point, stats.visible, stats.contained,
                act_k, stats.n_tot,
                jnp.asarray(table_k.frame), jnp.asarray(table_k.mask_id),
                np.int32(offset), np.int32(ci * self.f_chunk_pad),
                k_max=self.k_max, point_chunk=cfg.point_chunk,
                mask_visible_threshold=cfg.mask_visible_threshold,
                contained_threshold=cfg.contained_threshold,
                big_mask_point_count=cfg.big_mask_point_count,
                count_dtype=cfg.count_dtype)

            assignment, node_visible = self.assignment, self.node_visible
            if (ci + 1) % max(cfg.stream_recluster_every, 1) == 0 \
                    or ci + 1 == self.n_chunks:
                result = _stream_recluster_impl(
                    visible, contained, active, self.assignment,
                    max_len=cfg.max_cluster_iterations,
                    view_consensus_threshold=cfg.view_consensus_threshold,
                    count_dtype=cfg.count_dtype)
                assignment, node_visible = (result.assignment,
                                            result.node_visible)
                obs.count("stream.reclusters")

            # fold the chunk's claims into the point -> rep plane
            slot_of = np.full((self.f_chunk_pad, self.k_max + 2), -1,
                              dtype=np.int32)
            valid_rows = table_k.valid[:num_k]
            slot_of[table_k.frame[:num_k][valid_rows],
                    table_k.mask_id[:num_k][valid_rows]] = (
                offset + np.nonzero(valid_rows)[0])
            rep_plane, rep_votes, partial = _rep_plane_update_impl(
                self.rep_plane, self.rep_votes,
                assoc.first_id, assoc.last_id, jnp.asarray(slot_of),
                assignment,
                chunk_frames=self.chunk_frames,
                min_points=max(cfg.dbscan_split_min_points, 1))
            # sentinel: per-chunk accumulator digest (obs/digest.py) —
            # dispatched here, pulled inside the SAME sanctioned window as
            # the partial count, so the per-chunk host-sync contract
            # (two booked syncs) is unchanged
            from maskclustering_tpu.obs import digest as sentinel
            chunk_vec_dev = sentinel.digest_stream_device(
                assignment, active, rep_plane)
            # the anytime scalar: live partial-instance count, one 4-byte
            # pull (drains the chunk's dispatch chain)
            with sanctioned_pull("stream.partials"):
                partial = int(partial)
                chunk_vec = np.asarray(chunk_vec_dev)
            obs.count("stream.host_sync")
            # fault seam: scripted silent corruption of the pulled chunk
            # digest — surfaces only as drift, never as a retryable error
            if faults.take_corruption("chunk", self.seq_name):
                chunk_vec = chunk_vec.copy()
                chunk_vec[0] ^= 0x1

            # ---- transaction point: every program dispatched — bind ----
            with self._bind_lock:
                stale = epoch != self._epoch
                if stale:
                    # a retry (or a client resend) superseded this
                    # attempt while its watchdog-abandoned thread kept
                    # running: binding now would accumulate the chunk
                    # twice — abort on this (abandoned) thread (the
                    # counter + raise happen OUTSIDE the lock:
                    # CONC.BLOCKING forbids a second lock under it)
                    pass
                else:
                    self._bind_state(
                        visible, contained, active, n_tot, assignment,
                        node_visible, rep_plane, rep_votes, table_k,
                        offset, num_k, chunk_tensors, real_frames, partial)
            if stale:
                obs.count("stream.stale_binds_dropped")
                raise StaleChunkAttempt(self.seq_name, ci)
            if self.single:
                self._single_assoc = assoc
                self._single_points = np.asarray(padded.scene_points)  # mct-ok: AST.HOSTSYNC (host numpy; pad_scene_tensors keeps host frames host)
                self._single_frame_ids = list(padded.frame_ids)
                self._single_table = table_k
            state_bytes = sum(int(a.nbytes) for a in (
                self.visible, self.contained, self.active, self.n_tot,
                self.assignment, self.node_visible, self.rep_plane,
                self.rep_votes))
            obs.gauge_max("stream.state_bytes", float(state_bytes))
            obs.gauge("stream.partial_instances", float(partial))
            obs.count("stream.chunks")
            obs.count("stream.frames", real_frames)
            sp.set(partial_instances=partial, masks=self.masks_used)
        seconds = time.perf_counter() - t0
        self.timings["stream.chunks"] = (
            self.timings.get("stream.chunks", 0.0) + seconds)
        return {"chunk": ci, "frames": real_frames,
                "frames_done": self.frames_done,
                "total_frames": self.total_frames,
                "masks": self.masks_used,
                "partial_instances": partial,
                "plane_bytes": int(plane_bytes),
                "seconds": round(seconds, 4),
                "digest": sentinel.chunk_digest_hex(chunk_vec),
                "done": self.frames_done >= self.total_frames}

    # -- global table / export ----------------------------------------------

    def global_table(self) -> MaskTable:
        valid = np.zeros(self.m_pad, dtype=bool)
        valid[:self.masks_used] = self.g_mask_id[:self.masks_used] >= 0
        return MaskTable(frame=self.g_frame.copy(),
                         mask_id=self.g_mask_id.copy(), valid=valid,
                         num_masks=int(valid.sum()),
                         num_frames=self.total_frames, k_max=self.k_max)

    def partial_objects(self) -> SceneObjects:
        """Anytime partial instances from the current rep plane (the same
        export the finalize path uses, valid after any chunk)."""
        if self.chunks_done == 0:
            raise ValueError("partial_objects() before any chunk was pushed")
        return self._objects_from_rep_plane()

    def _objects_from_rep_plane(self) -> SceneObjects:
        cfg = self.cfg
        with sanctioned_pull("stream.rep_plane"):
            rep_h = np.asarray(self.rep_plane)[:self.n_real]
            assign_h = np.asarray(self.assignment)
            active_h = np.asarray(self.active)
        obs.count("stream.host_sync")
        member_count = np.bincount(assign_h[active_h], minlength=self.m_pad) \
            if active_h.any() else np.zeros(self.m_pad, np.int64)
        reps = np.unique(rep_h[rep_h > 0]) - 1
        reps = [int(r) for r in reps
                if member_count[r] >= cfg.min_masks_per_object]
        rep_points = {r: np.nonzero(rep_h == r + 1)[0] for r in reps}
        reps = [r for r in reps
                if len(rep_points[r]) >= cfg.dbscan_split_min_points]
        labels_by_rep = dict(zip(reps, dbscan_labels_parallel(
            [self.scene_points[rep_points[r]] for r in reps],
            cfg.dbscan_split_eps, cfg.dbscan_split_min_points)))
        members: Dict[int, List[int]] = {}
        for m in np.nonzero(active_h)[0]:
            members.setdefault(int(assign_h[m]), []).append(int(m))
        point_ids, bboxes, mask_lists = [], [], []
        for r in reps:
            pts = rep_points[r]
            labels = labels_by_rep[r]
            # noise (-1) keeps its own candidate group, like the batch
            # post-process's group 0
            for g in range(int(labels.max()) + 2):
                sel = (labels + 1) == g
                if not sel.any():
                    continue
                obj_pts = pts[sel]
                if len(obj_pts) < cfg.dbscan_split_min_points:
                    continue
                share = len(obj_pts) / max(len(pts), 1)
                # streaming approximation: the rep's whole mask list rides
                # every split component (per-mask point sets are not
                # retained at O(M^2) state; coverage is the component's
                # point share) — documented in ARCHITECTURE §Streaming
                mlist = [(self.frame_ids[self.g_frame[m]],
                          int(self.g_mask_id[m]), share)
                         for m in members.get(r, [])
                         if self.g_frame[m] < len(self.frame_ids)]
                if len(mlist) < cfg.min_masks_per_object:
                    continue
                pts3d = self.scene_points[obj_pts]
                point_ids.append(obj_pts)
                bboxes.append((pts3d.min(axis=0), pts3d.max(axis=0)))
                mask_lists.append(mlist)
        point_ids, mask_lists = _merge_overlapping(
            point_ids, bboxes, mask_lists, cfg.overlap_merge_ratio)
        return SceneObjects(point_ids_list=point_ids, mask_list=mask_lists,
                            num_points=self.n_real)

    def finalize(self, *, export: bool = False,
                 object_dict_dir: Optional[str] = None,
                 prediction_root: str = "data/prediction") -> SceneResult:
        """The stream's final answer.

        Single-chunk streams (chunk >= F) hand the chunk's planes plus the
        accumulated assignment to the EXACT batch host phase — artifacts
        byte-identical to ``run_scene`` by construction. Multi-chunk
        streams export from the rep plane (split + merge via the batch
        post-process helpers).
        """
        from maskclustering_tpu.models.pipeline import run_scene_host

        if self.chunks_done == 0:
            raise ValueError("finalize() before any chunk was pushed")
        if self.single:
            assoc = self._single_assoc
            handoff = DeviceHandoff(
                table=self._single_table, assignment=self.assignment,
                active=self.active, node_visible=self.node_visible,
                first_id=assoc.first_id, last_id=assoc.last_id,
                scene_points=self._single_points,
                frame_ids=self._single_frame_ids, k_max=self.k_max,
                n_real=self.n_real, seq_name=self.seq_name,
                timings=dict(self.timings))
            return run_scene_host(handoff, self.cfg, export=export,
                                  object_dict_dir=object_dict_dir,
                                  prediction_root=prediction_root)
        with obs.span("stream.finalize", scene=self.seq_name):
            objects = self._objects_from_rep_plane()
            with sanctioned_pull("stream.assignment"):
                assignment = np.asarray(self.assignment)
            if export:
                if self.seq_name is None or object_dict_dir is None:
                    raise ValueError(
                        "export=True requires seq_name and object_dict_dir")
                faults.inject("export", self.seq_name)
                export_artifacts(objects, self.seq_name,
                                 self.cfg.config_name, object_dict_dir,
                                 prediction_root=prediction_root,
                                 top_k_repre=self.cfg.num_representative_masks)
        from maskclustering_tpu.obs import digest as sentinel
        digest = sentinel.artifact_only_digest(
            objects,
            bucket=sentinel.bucket_label(self.k_max, self.f_chunk_pad,
                                         self.n_pad),
            count_dtype=self.cfg.count_dtype)
        return SceneResult(objects=objects, table=self.global_table(),
                           assignment=assignment,
                           timings=dict(self.timings), digest=digest)

    # -- accumulator journal (crash resume) ---------------------------------

    def save_state(self, path: str) -> None:
        """Atomic accumulator snapshot (multi-chunk streams only — a
        single-chunk stream re-runs its one chunk instead of persisting
        the full planes)."""
        if self.single:
            return
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        tmp = path + ".tmp.npz"
        # the journal drain IS a pull seam: the whole accumulator crosses
        # to host once per chunk, after the chunk's dispatch chain retired
        with sanctioned_pull("stream.state_journal"):
            np.savez(
                tmp,
                version=STREAM_STATE_VERSION,
                config_name=self.cfg.config_name,
                count_dtype=self.cfg.count_dtype,
                total_frames=self.total_frames,
                chunk_frames=self.chunk_frames,
                k_max=self.k_max,
                n_pad=self.n_pad,
                m_pad=self.m_pad,
                masks_used=self.masks_used,
                chunks_done=self.chunks_done,
                frames_done=self.frames_done,
                partial_instances=self.partial_instances,
                g_frame=self.g_frame, g_mask_id=self.g_mask_id,
                frame_ids=np.asarray(self.frame_ids, dtype=object),
                visible=np.asarray(self.visible),
                contained=np.asarray(self.contained),
                active=np.asarray(self.active),
                n_tot=np.asarray(self.n_tot),
                assignment=np.asarray(self.assignment),
                node_visible=np.asarray(self.node_visible),
                rep_plane=np.asarray(self.rep_plane),
                rep_votes=np.asarray(self.rep_votes),
                scene_points=self.scene_points,
            )
        os.replace(tmp, path)
        obs.count("stream.state_saves")

    def load_state(self, path: str) -> bool:
        """Resume from a journaled accumulator; False = not resumable
        (missing, torn, or a different stream's coordinates)."""
        if self.single or not os.path.exists(path):
            return False
        try:
            with np.load(path, allow_pickle=True) as z:
                if int(z["version"]) != STREAM_STATE_VERSION:
                    return False
                if (str(z["config_name"]) != self.cfg.config_name
                        or str(z["count_dtype"]) != self.cfg.count_dtype
                        or int(z["total_frames"]) != self.total_frames
                        or int(z["chunk_frames"]) != self.chunk_frames
                        or int(z["n_pad"]) != self.n_pad):
                    return False
                self.k_max = int(z["k_max"])
                self.m_pad = int(z["m_pad"])
                self.masks_used = int(z["masks_used"])
                self.chunks_done = int(z["chunks_done"])
                self.frames_done = int(z["frames_done"])
                self.partial_instances = int(z["partial_instances"])
                self.g_frame = z["g_frame"].copy()
                self.g_mask_id = z["g_mask_id"].copy()
                self.frame_ids = list(z["frame_ids"])
                self.visible = jnp.asarray(z["visible"])
                self.contained = jnp.asarray(z["contained"])
                self.active = jnp.asarray(z["active"])
                self.n_tot = jnp.asarray(z["n_tot"])
                self.assignment = jnp.asarray(z["assignment"])
                self.node_visible = jnp.asarray(z["node_visible"])
                self.rep_plane = jnp.asarray(z["rep_plane"])
                self.rep_votes = jnp.asarray(z["rep_votes"])
                self.scene_points = z["scene_points"].copy()
        except Exception:  # noqa: BLE001 — a torn snapshot restarts clean
            log.exception("stream %s: unreadable state journal %s "
                          "(restarting the stream)", self.seq_name, path)
            return False
        obs.count("stream.state_resumes")
        log.info("stream %s: resumed at chunk %d/%d from %s",
                 self.seq_name, self.chunks_done, self.n_chunks, path)
        return True


# ---------------------------------------------------------------------------
# the scene-level driver (run.py's streaming mode)
# ---------------------------------------------------------------------------


def stream_state_path(state_dir: str, seq_name: str) -> str:
    return os.path.join(state_dir, f"{seq_name}.stream.npz")


def stream_scene(tensors: SceneTensors, cfg: PipelineConfig, *,
                 seq_name: Optional[str] = None, export: bool = False,
                 object_dict_dir: Optional[str] = None,
                 prediction_root: str = "data/prediction",
                 state_dir: Optional[str] = None,
                 resume: bool = True) -> SceneResult:
    """Cluster one scene through the chunked streaming accumulator.

    The streaming analog of ``models.pipeline.run_scene``: frames feed in
    ``cfg.streaming_chunk``-sized chunks, a failed chunk retries (up to
    ``cfg.stream_chunk_retries``, device watchdog per chunk) with the
    accumulator intact, and — when ``state_dir`` is given — every chunk
    journals the accumulator so a killed process resumes mid-stream
    instead of restarting the scan.
    """
    from maskclustering_tpu.utils.compile_cache import max_seg_id

    k_max = bucket_k_max(max_seg_id(tensors.segmentations))
    acc = StreamAccumulator(cfg, total_frames=tensors.num_frames,
                            num_points=tensors.num_points, k_max=k_max,
                            seq_name=seq_name)
    state_path = (stream_state_path(state_dir, seq_name)
                  if state_dir and seq_name else None)
    if state_path and resume:
        acc.load_state(state_path)
    policy = faults.RetryPolicy(attempts=cfg.stream_chunk_retries + 1,
                                base_s=cfg.retry_backoff_s,
                                cap_s=max(cfg.retry_backoff_s * 8.0, 0.0))
    t0 = time.perf_counter()
    with obs.span("stream.scene", scene=seq_name,
                  chunks=acc.n_chunks, chunk_frames=acc.chunk_frames):
        for ci in range(acc.chunks_done, acc.n_chunks):
            chunk = slice_scene_frames(
                tensors, ci * acc.chunk_frames,
                min((ci + 1) * acc.chunk_frames, tensors.num_frames))
            attempt = 0
            while True:
                try:
                    digest = faults.call_with_deadline(
                        lambda chunk=chunk: acc.push_chunk(chunk),
                        cfg.watchdog_device_s, seam="device",
                        scene=seq_name)
                    break
                except Exception as e:  # noqa: BLE001 — chunk retry loop
                    if (faults.classify_error(e) == "terminal"
                            or attempt >= cfg.stream_chunk_retries
                            or faults.stop_requested()):
                        raise
                    attempt += 1
                    delay = policy.backoff(attempt)
                    obs.count("stream.chunk_retries")
                    log.warning("stream %s: chunk %d failed (%s); retry "
                                "%d/%d in %.2fs", seq_name, ci, e, attempt,
                                cfg.stream_chunk_retries, delay)
                    if delay > 0:
                        time.sleep(delay)
            log.info("stream %s: chunk %d/%d, %d frames, %d partial "
                     "instance(s)", seq_name, digest["chunk"] + 1,
                     acc.n_chunks, digest["frames_done"],
                     digest["partial_instances"])
            # snapshot cadence (cfg.stream_journal_every): every snapshot
            # drains the accumulator to host + writes an npz — real
            # latency against the per-chunk SLO at production M_pad — so
            # a >1 cadence trades at most N-1 re-runnable chunks on a
            # kill for N-1 snapshot-free chunks (0 = never). The FINAL
            # chunk never snapshots: finalize follows immediately and
            # deletes the file, so that drain would be pure waste (a
            # crash between here and finalize re-runs from artifacts)
            if state_path and cfg.stream_journal_every > 0 \
                    and (ci + 1) % cfg.stream_journal_every == 0 \
                    and ci + 1 < acc.n_chunks:
                acc.save_state(state_path)
        result = faults.call_with_deadline(
            lambda: acc.finalize(export=export,
                                 object_dict_dir=object_dict_dir,
                                 prediction_root=prediction_root),
            cfg.watchdog_host_s, seam="host", scene=seq_name)
    if state_path and os.path.exists(state_path):
        # the scene is done: the state journal must not resume a finished
        # stream into a double-accumulation
        os.remove(state_path)
    timings = dict(result.timings)
    timings["stream.total"] = round(time.perf_counter() - t0, 4)
    timings["stream.num_chunks"] = float(acc.n_chunks)
    return SceneResult(objects=result.objects, table=result.table,
                       assignment=result.assignment, timings=timings,
                       digest=result.digest)
