"""Cluster post-processing and artifact export.

Reproduces the reference's post pipeline (utils/post_process.py:173-195):
per node with >= 2 masks, (i) DBSCAN-split the node's point cloud into
spatially connected objects, (ii) drop points whose detection ratio within
the node is below threshold (OVIR-3D filter), (iii) drop objects with < 2
assigned masks, then (iv) merge objects with > 0.8 point overlap, and
export the class-agnostic npz + object_dict artifacts bit-compatibly with
the reference's evaluator contract (post_process.py:131-170).

This stage is off the hot path (a few hundred objects, reference's own
implementation is host numpy), so it runs on host with vectorized numpy
over the COO structures produced by the device stages; DBSCAN dispatches to
the native C++ extension when built, else sklearn.
"""

from __future__ import annotations

import os
import time
from typing import Dict, List, NamedTuple, Optional, Sequence, Tuple

import numpy as np

from maskclustering_tpu.ops.dbscan import dbscan_labels_parallel
from maskclustering_tpu.ops.geometry import bboxes_overlap


class _PhaseTimer:
    """Optional phase wall-times accumulated into a caller-owned dict."""

    def __init__(self, timings: Optional[Dict[str, float]]):
        self.timings = timings
        self.last = time.perf_counter()

    def mark(self, name: str) -> None:
        if self.timings is not None:
            now = time.perf_counter()
            self.timings[name] = self.timings.get(name, 0.0) + now - self.last
            self.last = now


class SceneObjects(NamedTuple):
    """Final per-scene objects plus the artifacts' raw ingredients."""

    point_ids_list: List[np.ndarray]
    mask_list: List[List[Tuple]]  # per object: [(frame_id, mask_id, coverage), ...]
    num_points: int


def _claims_coo(first: np.ndarray, last: np.ndarray, gmap: np.ndarray):
    """COO arrays (global_mask, point, frame) of every (point, mask) claim.

    first/last: (F, N) integer claiming ids per point per frame (0 = none;
    int16 since the plane narrowing — every op here is width-agnostic).
    gmap: (F, K+1) -> global mask index or -1.

    Each (frame, point) cell contributes at most two claims, and they
    coincide exactly when last == first — so masking the duplicate out of
    ``last`` replaces the multi-million-row ``np.unique(axis=0)`` sort
    (the dominant postprocess cost at bench scale) with one boolean
    compare.
    """
    coords = []
    last_dedup = np.where(last == first, 0, last)
    for arr in (first, last_dedup):
        f_idx, p_idx = np.nonzero(arr)
        m = gmap[f_idx, arr[f_idx, p_idx]]
        ok = m >= 0
        coords.append((m[ok], p_idx[ok], f_idx[ok]))
    m_coo = np.concatenate([c[0] for c in coords])
    p_coo = np.concatenate([c[1] for c in coords])
    f_coo = np.concatenate([c[2] for c in coords])
    return m_coo, p_coo, f_coo


def postprocess_scene(
    scene_points: np.ndarray,  # (N, 3)
    first: np.ndarray,  # (F, N) int16 (any int width works)
    last: np.ndarray,  # (F, N) int16
    point_visible: np.ndarray,  # (F, N) bool
    mask_frame: np.ndarray,  # (M_pad,) int32
    mask_id: np.ndarray,  # (M_pad,) int32
    mask_active: np.ndarray,  # (M_pad,) bool — valid & not undersegmented
    assignment: np.ndarray,  # (M_pad,) int32 final cluster representative
    node_visible: np.ndarray,  # (M_pad, F) bool aggregated per representative
    frame_ids: Sequence,  # original frame identifiers, len F
    *,
    k_max: int = 127,
    point_filter_threshold: float = 0.5,
    dbscan_eps: float = 0.1,
    dbscan_min_points: int = 4,
    overlap_merge_ratio: float = 0.8,
    min_masks_per_object: int = 2,
    timings: Optional[Dict[str, float]] = None,
) -> SceneObjects:
    t = _PhaseTimer(timings)
    f, n = first.shape
    m_pad = mask_frame.shape[0]

    gmap = np.full((f, k_max + 2), -1, dtype=np.int64)
    act_idx = np.nonzero(mask_active)[0]
    gmap[mask_frame[act_idx], mask_id[act_idx]] = act_idx

    m_coo, p_coo, f_coo = _claims_coo(first, last, gmap)
    rep_coo = assignment[m_coo].astype(np.int64)
    t.mark("claims")

    # per-mask point sets (sorted by mask)
    order = np.argsort(m_coo, kind="stable")
    m_sorted, p_by_mask = m_coo[order], p_coo[order]

    # node sizes: count of active member masks per representative
    sizes = np.bincount(assignment[mask_active], minlength=m_pad)
    reps = np.nonzero(sizes >= min_masks_per_object)[0]

    # ONE sort builds both node structures: unique claimed (rep, point, frame)
    # triples, and — because the triple keys are sorted by (rep, point) first —
    # the unique (rep, point) node rows fall out with a flag diff, no 2nd sort.
    rpf_key = np.sort((rep_coo * n + p_coo) * f + f_coo)
    new_tri = np.empty(len(rpf_key), dtype=bool)
    if len(rpf_key):
        new_tri[0] = True
        new_tri[1:] = rpf_key[1:] != rpf_key[:-1]
    rpf_key = rpf_key[new_tri]
    rpf_pf = rpf_key // f
    rpf_f = (rpf_key % f).astype(np.int32)

    new_rp = np.empty(len(rpf_pf), dtype=bool)
    if len(rpf_pf):
        new_rp[0] = True
        new_rp[1:] = rpf_pf[1:] != rpf_pf[:-1]
    rp_key = rpf_pf[new_rp]
    rp_rep = (rp_key // n).astype(np.int32)
    rp_pt = (rp_key % n).astype(np.int32)
    row_of_tri = np.cumsum(new_rp) - 1  # triple -> its (rep, point) row
    rp_starts = np.searchsorted(rp_rep, np.arange(m_pad + 1))
    t.mark("node_structs")

    # ---- detection ratio, vectorized over ALL (rep, point) rows at once ----
    # numerator: #frames where the point is claimed by a node mask
    tri_rep = (rpf_pf // n).astype(np.int32)
    tri_ok = node_visible[tri_rep, rpf_f]
    num = np.bincount(row_of_tri[tri_ok], minlength=len(rp_key)).astype(np.float64)
    # denominator: #node frames where the point is visible at all
    # (chunked (rows, F) gather keeps peak memory bounded)
    den = np.empty(len(rp_key), dtype=np.float64)
    pv_t = point_visible.T  # (N, F)
    chunk = 1 << 20
    for s in range(0, len(rp_key), chunk):
        e = min(s + chunk, len(rp_key))
        den[s:e] = (node_visible[rp_rep[s:e]] & pv_t[rp_pt[s:e]]).sum(axis=1)
    ratio_ok_rows = num / (den + 1e-6) > point_filter_threshold
    t.mark("ratio")

    # ---- DBSCAN split each node; group labels live in one global array ----
    # glabel[row] = group_offset[rep] + (dbscan label + 1); 0-label = noise is
    # kept as its own candidate object (reference post_process.py:109-123)
    glabel = np.full(len(rp_key), -1, dtype=np.int64)
    rep_offset = np.zeros(m_pad, dtype=np.int64)  # group_offset per rep
    rep_groups = np.zeros(m_pad, dtype=np.int64)  # group count per live rep
    rep_slices: List[Tuple[int, int, int, np.ndarray]] = []  # (rep, s, e, groups)
    candidates = [rep for rep in reps
                  if rp_starts[rep + 1] > rp_starts[rep] and node_visible[rep].any()]
    labels_by_rep = dict(zip(candidates, dbscan_labels_parallel(
        [scene_points[rp_pt[rp_starts[r]:rp_starts[r + 1]]] for r in candidates],
        dbscan_eps, dbscan_min_points)))
    group_offset = 0
    for rep in candidates:
        s, e = rp_starts[rep], rp_starts[rep + 1]
        labels = labels_by_rep[rep]
        groups = labels + 1
        glabel[s:e] = group_offset + groups
        rep_offset[rep] = group_offset
        rep_groups[rep] = int(groups.max()) + 1
        rep_slices.append((int(rep), int(s), int(e), groups))
        group_offset += int(groups.max()) + 1
    total_groups = max(group_offset, 1)
    group_size = np.bincount(glabel[glabel >= 0], minlength=total_groups)
    t.mark("dbscan")

    # ---- assign each member mask to its best-overlapping group ----
    # Every claimed point of a mask is a node point of its rep, so the
    # mask∩group intersection is a count of the mask's claims per group of
    # its OWN rep — so a (mask, local-group) slot table is dense and small
    # (Σ members × groups-of-their-rep) and one O(C) bincount replaces the
    # per-(mask × group) intersect1d loop (and any O(C log C) sort).
    g_of_mask = rep_groups[assignment]  # (m_pad,) slots per mask
    slot_base = np.zeros(m_pad + 1, dtype=np.int64)
    np.cumsum(g_of_mask, out=slot_base[1:])
    claim_row = np.searchsorted(rp_key, rep_coo[order] * n + p_by_mask)
    claim_gl = glabel[claim_row]
    ok = claim_gl >= 0
    m_ok = m_sorted[ok]
    key = slot_base[m_ok] + (claim_gl[ok] - rep_offset[assignment[m_ok]])
    counts = np.bincount(key, minlength=slot_base[-1]).astype(np.int64)
    # per-mask argmax over its slot segment: pack (count, lowest-index wins)
    # into one int64 so np.maximum.reduceat resolves ties like the
    # reference's ascending scan with a strict > (post_process.py:~150)
    ln = max(len(counts), 1)
    packed = counts * ln + (ln - 1 - np.arange(len(counts), dtype=np.int64))
    # segment boundaries must cover every non-empty slot run (masks with zero
    # slots occupy zero width, so consecutive starts still tile `counts`);
    # inactive masks have no claims, land at cnt == 0, and are skipped below
    seg_masks = np.nonzero(g_of_mask > 0)[0]
    seg_starts = slot_base[seg_masks]
    obj_masks: Dict[int, List[Tuple]] = {}
    if len(seg_starts):
        seg_best = np.maximum.reduceat(packed, seg_starts)
        best_cnt = seg_best // ln
        best_slot = ln - 1 - (seg_best % ln)
        best_gl = best_slot - slot_base[seg_masks] + rep_offset[assignment[seg_masks]]
        for m, gl, cnt in zip(seg_masks, best_gl, best_cnt):
            if cnt <= 0:  # mask with no surviving claims (all mid-id overlaps)
                continue
            obj_masks.setdefault(int(gl), []).append(
                (frame_ids[mask_frame[m]], int(mask_id[m]), float(cnt / group_size[gl]))
            )
    t.mark("mask_assign")

    total_point_ids: List[np.ndarray] = []
    total_bboxes: List[Tuple[np.ndarray, np.ndarray]] = []
    total_masks: List[List[Tuple]] = []

    for rep, s, e, groups in rep_slices:
        node_pts = rp_pt[s:e]
        ratio_ok = ratio_ok_rows[s:e]
        base = glabel[s]  # group_offset of this rep (groups[0] may be noise 0)
        base -= groups[0]
        for g in range(int(groups.max()) + 1):
            sel = groups == g
            if not sel.any():
                continue
            masks_g = obj_masks.get(int(base + g), [])
            obj_pts_all = node_pts[sel]
            obj_pts = obj_pts_all[ratio_ok[sel]]
            if len(obj_pts) == 0 or len(masks_g) < min_masks_per_object:
                continue
            pts3d = scene_points[obj_pts_all]
            total_point_ids.append(obj_pts)
            total_bboxes.append((pts3d.min(axis=0), pts3d.max(axis=0)))
            total_masks.append(masks_g)

    t.mark("emit")
    point_ids_list, mask_list = _merge_overlapping(
        total_point_ids, total_bboxes, total_masks, overlap_merge_ratio
    )
    t.mark("merge")
    return SceneObjects(point_ids_list=point_ids_list, mask_list=mask_list, num_points=n)


def _merge_overlapping(point_ids_list, bbox_list, mask_list, overlap_ratio: float):
    """Greedy pairwise overlap suppression (reference post_process.py:7-37).

    Scan order and the "first passing test wins" asymmetry are preserved:
    if |i∩j|/|i| > r, object i dies; elif |i∩j|/|j| > r, object j dies.
    """
    num = len(point_ids_list)
    dead = np.zeros(num, dtype=bool)
    sets = [frozenset(p.tolist()) for p in point_ids_list]
    for i in range(num):
        if dead[i]:
            continue
        for j in range(i + 1, num):
            if dead[j]:
                continue
            (imin, imax), (jmin, jmax) = bbox_list[i], bbox_list[j]
            if not bboxes_overlap(imin, imax, jmin, jmax):
                continue
            inter = len(sets[i] & sets[j])
            if inter / max(len(sets[i]), 1) > overlap_ratio:
                dead[i] = True
                # no break: the reference keeps scanning j with dead i, and a
                # later j can still die via the elif branch
            elif inter / max(len(sets[j]), 1) > overlap_ratio:
                dead[j] = True
    keep = [k for k in range(num) if not dead[k]]
    return [point_ids_list[k] for k in keep], [mask_list[k] for k in keep]


def merge_from_counts(point_ids_list, bbox_list, mask_list, sizes, inter,
                      overlap_ratio: float):
    """`_merge_overlapping` with precomputed intersection counts.

    The device post-process computes ``inter[i, j] = |points_i ∩ points_j|``
    as one mask×mask counting matmul on device (the O(objects² × N) work);
    this host scan replays the reference's greedy suppression over those
    exact integers — scan order, the first-passing-test-wins asymmetry and
    the f64 ratio comparisons are all byte-identical to the set-based
    loop above (pinned by tests/test_postprocess_device.py).
    """
    num = len(point_ids_list)
    dead = np.zeros(num, dtype=bool)
    for i in range(num):
        if dead[i]:
            continue
        for j in range(i + 1, num):
            if dead[j]:
                continue
            (imin, imax), (jmin, jmax) = bbox_list[i], bbox_list[j]
            if not bboxes_overlap(imin, imax, jmin, jmax):
                continue
            x = int(inter[i, j])
            if x / max(int(sizes[i]), 1) > overlap_ratio:
                dead[i] = True
                # no break: the reference keeps scanning j with dead i, and a
                # later j can still die via the elif branch
            elif x / max(int(sizes[j]), 1) > overlap_ratio:
                dead[j] = True
    keep = [k for k in range(num) if not dead[k]]
    return [point_ids_list[k] for k in keep], [mask_list[k] for k in keep]


def representative_masks(mask_info_list: List[Tuple], top_k: int = 5) -> List[Tuple]:
    """Top-k masks by object coverage (reference post_process.py:126-128)."""
    return sorted(mask_info_list, key=lambda t: t[2], reverse=True)[:top_k]


def export_artifacts(objects: SceneObjects, seq_name: str, config_name: str,
                     object_dict_dir: str, prediction_root: str = "data/prediction",
                     top_k_repre: int = 5) -> Dict[str, str]:
    """Write the class-agnostic npz + object_dict.npy artifact pair.

    Formats match the reference exactly (post_process.py:131-170) so the
    evaluation protocol and the semantics stage read either framework's
    output interchangeably.
    """
    from maskclustering_tpu import obs

    with obs.span("export", scene=seq_name,
                  num_objects=len(objects.point_ids_list)):
        return _export_artifacts(objects, seq_name, config_name,
                                 object_dict_dir, prediction_root, top_k_repre)


def _export_artifacts(objects: SceneObjects, seq_name: str, config_name: str,
                      object_dict_dir: str, prediction_root: str,
                      top_k_repre: int) -> Dict[str, str]:
    num_instance = len(objects.point_ids_list)
    masks = np.zeros((objects.num_points, max(num_instance, 0)), dtype=bool)
    object_dict = {}
    for i, (pids, mlist) in enumerate(zip(objects.point_ids_list, objects.mask_list)):
        masks[pids, i] = True
        object_dict[i] = {
            "point_ids": np.asarray(pids),
            "mask_list": mlist,
            "repre_mask_list": representative_masks(mlist, top_k_repre),
        }

    # tmp + rename: artifact files must appear ATOMICALLY. The resume check
    # is a bare exists() (run._load_for_cluster), and the overlapped
    # executor writes from a worker thread a process exit can kill
    # mid-write — a truncated npz left at the final path would make the
    # scene "done" forever with a corrupt artifact.
    ca_dir = os.path.join(prediction_root, config_name + "_class_agnostic")
    os.makedirs(ca_dir, exist_ok=True)
    npz_path = os.path.join(ca_dir, f"{seq_name}.npz")
    tmp = npz_path + ".tmp.npz"  # np.savez appends .npz to unknown suffixes
    np.savez(
        tmp,
        pred_masks=masks,
        pred_score=np.ones(num_instance),
        pred_classes=np.zeros(num_instance, dtype=np.int32),
    )
    os.replace(tmp, npz_path)

    od_dir = os.path.join(object_dict_dir, config_name)
    os.makedirs(od_dir, exist_ok=True)
    od_path = os.path.join(od_dir, "object_dict.npy")
    tmp = od_path + ".tmp.npy"  # np.save likewise appends .npy
    np.save(tmp, object_dict, allow_pickle=True)
    os.replace(tmp, od_path)
    return {"npz": npz_path, "object_dict": od_path}
