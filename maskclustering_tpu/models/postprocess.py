"""Cluster post-processing and artifact export.

Reproduces the reference's post pipeline (utils/post_process.py:173-195):
per node with >= 2 masks, (i) DBSCAN-split the node's point cloud into
spatially connected objects, (ii) drop points whose detection ratio within
the node is below threshold (OVIR-3D filter), (iii) drop objects with < 2
assigned masks, then (iv) merge objects with > 0.8 point overlap, and
export the class-agnostic npz + object_dict artifacts bit-compatibly with
the reference's evaluator contract (post_process.py:131-170).

This stage is off the hot path (a few hundred objects, reference's own
implementation is host numpy), so it runs on host with vectorized numpy
over the COO structures produced by the device stages; DBSCAN dispatches to
the native C++ extension when built, else sklearn.
"""

from __future__ import annotations

import os
from typing import Dict, List, NamedTuple, Sequence, Tuple

import numpy as np

from maskclustering_tpu.ops.dbscan import dbscan_labels
from maskclustering_tpu.ops.geometry import bboxes_overlap


class SceneObjects(NamedTuple):
    """Final per-scene objects plus the artifacts' raw ingredients."""

    point_ids_list: List[np.ndarray]
    mask_list: List[List[Tuple]]  # per object: [(frame_id, mask_id, coverage), ...]
    num_points: int


def _claims_coo(first: np.ndarray, last: np.ndarray, gmap: np.ndarray):
    """COO arrays (global_mask, point, frame) of every (point, mask) claim.

    first/last: (F, N) int32 claiming ids per point per frame (0 = none).
    gmap: (F, K+1) -> global mask index or -1.

    Each (frame, point) cell contributes at most two claims, and they
    coincide exactly when last == first — so masking the duplicate out of
    ``last`` replaces the multi-million-row ``np.unique(axis=0)`` sort
    (the dominant postprocess cost at bench scale) with one boolean
    compare.
    """
    coords = []
    last_dedup = np.where(last == first, 0, last)
    for arr in (first, last_dedup):
        f_idx, p_idx = np.nonzero(arr)
        m = gmap[f_idx, arr[f_idx, p_idx]]
        ok = m >= 0
        coords.append((m[ok], p_idx[ok], f_idx[ok]))
    m_coo = np.concatenate([c[0] for c in coords])
    p_coo = np.concatenate([c[1] for c in coords])
    f_coo = np.concatenate([c[2] for c in coords])
    return m_coo, p_coo, f_coo


def postprocess_scene(
    scene_points: np.ndarray,  # (N, 3)
    first: np.ndarray,  # (F, N) int32
    last: np.ndarray,  # (F, N) int32
    point_visible: np.ndarray,  # (F, N) bool
    mask_frame: np.ndarray,  # (M_pad,) int32
    mask_id: np.ndarray,  # (M_pad,) int32
    mask_active: np.ndarray,  # (M_pad,) bool — valid & not undersegmented
    assignment: np.ndarray,  # (M_pad,) int32 final cluster representative
    node_visible: np.ndarray,  # (M_pad, F) bool aggregated per representative
    frame_ids: Sequence,  # original frame identifiers, len F
    *,
    k_max: int = 127,
    point_filter_threshold: float = 0.5,
    dbscan_eps: float = 0.1,
    dbscan_min_points: int = 4,
    overlap_merge_ratio: float = 0.8,
    min_masks_per_object: int = 2,
) -> SceneObjects:
    f, n = first.shape
    m_pad = mask_frame.shape[0]

    gmap = np.full((f, k_max + 2), -1, dtype=np.int64)
    act_idx = np.nonzero(mask_active)[0]
    gmap[mask_frame[act_idx], mask_id[act_idx]] = act_idx

    m_coo, p_coo, f_coo = _claims_coo(first, last, gmap)
    rep_coo = assignment[m_coo]

    # per-mask point sets (sorted by mask)
    order = np.argsort(m_coo, kind="stable")
    m_sorted, p_by_mask = m_coo[order], p_coo[order]
    mask_starts = np.searchsorted(m_sorted, np.arange(m_pad + 1))

    def mask_points(m):
        return p_by_mask[mask_starts[m]: mask_starts[m + 1]]

    # node sizes: count of active member masks per representative
    sizes = np.bincount(assignment[mask_active], minlength=m_pad)
    reps = np.nonzero(sizes >= min_masks_per_object)[0]

    # node point sets: unique (rep, point) via packed 1-D int64 keys —
    # an order of magnitude faster than np.unique(axis=0)'s row sort
    rp_key = np.unique(rep_coo.astype(np.int64) * n + p_coo)
    rp = np.stack([rp_key // n, rp_key % n], axis=1)
    rp_starts = np.searchsorted(rp[:, 0], np.arange(m_pad + 1))

    # node claimed (rep, point, frame) triples, deduped the same way
    rpf_key = np.unique((rep_coo.astype(np.int64) * n + p_coo) * f + f_coo)
    rpf_pf, rpf_f = rpf_key // f, rpf_key % f
    rpf = np.stack([rpf_pf // n, rpf_pf % n, rpf_f], axis=1)
    rpf_starts = np.searchsorted(rpf[:, 0], np.arange(m_pad + 1))

    members_by_rep: Dict[int, np.ndarray] = {}
    for m in act_idx:
        members_by_rep.setdefault(int(assignment[m]), []).append(int(m))

    total_point_ids: List[np.ndarray] = []
    total_bboxes: List[Tuple[np.ndarray, np.ndarray]] = []
    total_masks: List[List[Tuple]] = []

    pv = point_visible  # (F, N)
    for rep in reps:
        node_pts = rp[rp_starts[rep]: rp_starts[rep + 1], 1]
        if len(node_pts) == 0:
            continue
        node_frames = np.nonzero(node_visible[rep])[0]
        if len(node_frames) == 0:
            continue

        # ---- detection ratio over the node's frames ----
        # denominator: #node frames where the point is visible at all
        # (np.ix_ selects the node's own points before materializing)
        den = pv[np.ix_(node_frames, node_pts)].sum(axis=0).astype(np.float64)
        # numerator: #node frames where the point is claimed by a node mask
        tri = rpf[rpf_starts[rep]: rpf_starts[rep + 1]]
        tri = tri[np.isin(tri[:, 2], node_frames)]
        pos = np.searchsorted(node_pts, tri[:, 1])
        num = np.bincount(pos, minlength=len(node_pts)).astype(np.float64)
        ratio_ok = num / (den + 1e-6) > point_filter_threshold

        # ---- DBSCAN split into spatially connected objects ----
        labels = dbscan_labels(scene_points[node_pts], eps=dbscan_eps,
                               min_points=dbscan_min_points)
        groups = labels + 1  # group 0 = noise, kept as its own candidate object
        # (the reference keeps the noise group too, post_process.py:109-123)

        # ---- assign each member mask to its best-overlapping object ----
        group_ids = np.unique(groups)
        group_sets = {g: node_pts[groups == g] for g in group_ids}
        obj_masks: Dict[int, List[Tuple]] = {g: [] for g in group_ids}
        for m in members_by_rep.get(int(rep), []):
            mp = mask_points(m)
            best_g, best_inter = -1, 0
            best_cov = 0.0
            for g in group_ids:
                inter = np.intersect1d(mp, group_sets[g], assume_unique=False).size
                if inter > best_inter:
                    best_g, best_inter = g, inter
                    best_cov = inter / len(group_sets[g])
            if best_inter > 0:
                obj_masks[best_g].append(
                    (frame_ids[mask_frame[m]], int(mask_id[m]), float(best_cov))
                )

        for g in group_ids:
            sel = groups == g
            obj_pts_all = node_pts[sel]
            obj_pts = obj_pts_all[ratio_ok[sel]]
            if len(obj_pts) == 0 or len(obj_masks[g]) < min_masks_per_object:
                continue
            pts3d = scene_points[obj_pts_all]
            total_point_ids.append(obj_pts)
            total_bboxes.append((pts3d.min(axis=0), pts3d.max(axis=0)))
            total_masks.append(obj_masks[g])

    point_ids_list, mask_list = _merge_overlapping(
        total_point_ids, total_bboxes, total_masks, overlap_merge_ratio
    )
    return SceneObjects(point_ids_list=point_ids_list, mask_list=mask_list, num_points=n)


def _merge_overlapping(point_ids_list, bbox_list, mask_list, overlap_ratio: float):
    """Greedy pairwise overlap suppression (reference post_process.py:7-37).

    Scan order and the "first passing test wins" asymmetry are preserved:
    if |i∩j|/|i| > r, object i dies; elif |i∩j|/|j| > r, object j dies.
    """
    num = len(point_ids_list)
    dead = np.zeros(num, dtype=bool)
    sets = [frozenset(p.tolist()) for p in point_ids_list]
    for i in range(num):
        if dead[i]:
            continue
        for j in range(i + 1, num):
            if dead[j]:
                continue
            (imin, imax), (jmin, jmax) = bbox_list[i], bbox_list[j]
            if not bboxes_overlap(imin, imax, jmin, jmax):
                continue
            inter = len(sets[i] & sets[j])
            if inter / max(len(sets[i]), 1) > overlap_ratio:
                dead[i] = True
                # no break: the reference keeps scanning j with dead i, and a
                # later j can still die via the elif branch
            elif inter / max(len(sets[j]), 1) > overlap_ratio:
                dead[j] = True
    keep = [k for k in range(num) if not dead[k]]
    return [point_ids_list[k] for k in keep], [mask_list[k] for k in keep]


def representative_masks(mask_info_list: List[Tuple], top_k: int = 5) -> List[Tuple]:
    """Top-k masks by object coverage (reference post_process.py:126-128)."""
    return sorted(mask_info_list, key=lambda t: t[2], reverse=True)[:top_k]


def export_artifacts(objects: SceneObjects, seq_name: str, config_name: str,
                     object_dict_dir: str, prediction_root: str = "data/prediction",
                     top_k_repre: int = 5) -> Dict[str, str]:
    """Write the class-agnostic npz + object_dict.npy artifact pair.

    Formats match the reference exactly (post_process.py:131-170) so the
    evaluation protocol and the semantics stage read either framework's
    output interchangeably.
    """
    num_instance = len(objects.point_ids_list)
    masks = np.zeros((objects.num_points, max(num_instance, 0)), dtype=bool)
    object_dict = {}
    for i, (pids, mlist) in enumerate(zip(objects.point_ids_list, objects.mask_list)):
        masks[pids, i] = True
        object_dict[i] = {
            "point_ids": np.asarray(pids),
            "mask_list": mlist,
            "repre_mask_list": representative_masks(mlist, top_k_repre),
        }

    ca_dir = os.path.join(prediction_root, config_name + "_class_agnostic")
    os.makedirs(ca_dir, exist_ok=True)
    npz_path = os.path.join(ca_dir, f"{seq_name}.npz")
    np.savez(
        npz_path,
        pred_masks=masks,
        pred_score=np.ones(num_instance),
        pred_classes=np.zeros(num_instance, dtype=np.int32),
    )

    od_dir = os.path.join(object_dict_dir, config_name)
    os.makedirs(od_dir, exist_ok=True)
    od_path = os.path.join(od_dir, "object_dict.npy")
    np.save(od_path, object_dict, allow_pickle=True)
    return {"npz": npz_path, "object_dict": od_path}
