"""Exact-parity mask backprojection: the reference's ball-query pipeline.

The default association path (models/backprojection.py) inverts the search
direction for TPU efficiency. This module instead reproduces the
reference's per-mask pipeline step by step (utils/mask_backprojection.py:
70-151) for parity validation and A/B studies, selected with
``PipelineConfig.use_exact_ball_query``:

per frame: depth -> view cloud; per mask: pixel backprojections ->
voxel downsample (r = distance_threshold) -> DBSCAN denoise keeping
components >= 20% + statistical outlier removal (geometry.py:9-24) ->
strict bbox crop of the scene cloud (mask_backprojection.py:48-67) ->
batched ball query K=20 r=distance_threshold over padded masks
(mask_backprojection.py:123-128) -> coverage >= 0.3 test (143-145); then
the frame's masks are written into the point-in-mask matrix in ascending
mask-id order with shared points zeroed as boundary
(construction.py:46-62).

The ball query runs on-device (the Pallas TPU kernel when available, the
jnp fallback otherwise); the per-mask preprocessing is host numpy like the
reference's Open3D calls — this is the fidelity path, not the fast path.
"""

from __future__ import annotations

import logging
from typing import Dict, List, Tuple

import jax.numpy as jnp
import numpy as np

log = logging.getLogger("maskclustering_tpu")
_PALLAS_WARNED = False

from maskclustering_tpu.models.backprojection import SceneAssociation
from maskclustering_tpu.ops.dbscan import dbscan_labels
from maskclustering_tpu.ops.geometry import voxel_downsample_np


def statistical_outlier_mask(points: np.ndarray, nb_neighbors: int = 20,
                             std_ratio: float = 2.0) -> np.ndarray:
    """Keep-mask of Open3D remove_statistical_outlier semantics.

    Per point: mean distance to its nb_neighbors nearest neighbors, where —
    matching Open3D's KNN, whose search set includes the query point itself
    at distance 0 — the point's own zero distance occupies one of the
    nb_neighbors slots. Keep points whose mean distance <= global_mean +
    std_ratio * global_std. KD-tree KNN (exact) when scipy is present; the
    brute-force O(P^2) fallback made large masks cost ~10 s each at the
    reference radius.
    """
    p = len(points)
    if p <= 1:
        return np.ones(p, dtype=bool)
    nb = min(nb_neighbors, p)
    try:
        from scipy.spatial import cKDTree

        dist, _ = cKDTree(points).query(points, k=nb)
        mean_dist = dist.reshape(p, nb).mean(axis=1)
    except ImportError:  # pragma: no cover - scipy ships with sklearn here
        d2 = np.sum((points[:, None, :] - points[None, :, :]) ** 2, axis=-1)
        nearest = np.sort(d2, axis=1)[:, :nb]  # row min is the self-distance 0
        mean_dist = np.sqrt(np.maximum(nearest, 0.0)).mean(axis=1)
    mu, sigma = mean_dist.mean(), mean_dist.std()
    return mean_dist <= mu + std_ratio * sigma


def denoise_mask_points(points: np.ndarray, eps: float = 0.04,
                        min_points: int = 4) -> np.ndarray:
    """Reference utils/geometry.py denoise: DBSCAN components >= 20% of the
    cloud survive, then statistical outlier removal. Returns kept indices."""
    if len(points) == 0:
        return np.zeros(0, dtype=np.int64)
    labels = dbscan_labels(points, eps=eps, min_points=min_points) + 1
    counts = np.bincount(labels)
    keep = counts[labels] >= 0.2 * len(labels)
    remain = np.nonzero(keep)[0]
    if len(remain) == 0:
        return remain
    inlier = statistical_outlier_mask(points[remain])
    return remain[inlier]


def _frame_view_points(depth: np.ndarray, intrinsics: np.ndarray,
                       cam_to_world: np.ndarray, depth_trunc: float):
    """Valid-depth pixel backprojections in world frame + flat valid mask."""
    from maskclustering_tpu.ops.geometry import backproject_depth_np

    pts, valid = backproject_depth_np(depth, intrinsics, cam_to_world, depth_trunc)
    return pts, valid.reshape(-1)


def _pow2(value: int, minimum: int) -> int:
    return 1 << max(minimum, int(np.ceil(np.log2(max(value, 1)))))


def _ball_query_kdtree(q, c, ql, cl, k, radius):
    """scipy KD-tree ball query, identical semantics to ops/neighbor.py:
    first K candidates within radius in ASCENDING INDEX order, -1 padded
    (pytorch3d ball_query contract, reference mask_backprojection.py:38)."""
    from scipy.spatial import cKDTree

    b, p_pad, _ = q.shape
    out = np.full((b, p_pad, k), -1, dtype=np.int32)
    for bi in range(b):
        nq, nc = int(ql[bi]), int(cl[bi])
        if nq == 0 or nc == 0:
            continue
        tree = cKDTree(c[bi, :nc])
        hits = tree.query_ball_point(q[bi, :nq], r=radius, return_sorted=True)
        for pi, idxs in enumerate(hits):
            if idxs:
                take = idxs[:k]
                out[bi, pi, : len(take)] = take
    return out


def _ball_query_group(q, c, ql, cl, k, radius):
    """One padded ball-query batch (Pallas on TPU, KD-tree on host CPU)."""
    from maskclustering_tpu.ops.neighbor import ball_query

    try:  # Pallas TPU kernel when the backend supports it
        import jax

        if jax.default_backend() == "tpu":
            from maskclustering_tpu.ops.pallas.ball_query import ball_query_pallas

            return np.asarray(ball_query_pallas(
                jnp.asarray(q), jnp.asarray(c), jnp.asarray(ql), jnp.asarray(cl),
                k=k, radius=radius))
    except Exception:  # pragma: no cover - fall through to the jnp path
        global _PALLAS_WARNED
        if not _PALLAS_WARNED:  # a real Mosaic lowering failure must be
            _PALLAS_WARNED = True  # visible, not a silent perf regression
            log.warning("Pallas ball_query failed; using the jnp fallback",
                        exc_info=True)
    try:
        return _ball_query_kdtree(q, c, ql, cl, k, radius)
    except ImportError:  # pragma: no cover - scipy ships with sklearn here
        return np.asarray(ball_query(
            jnp.asarray(q), jnp.asarray(c), jnp.asarray(ql), jnp.asarray(cl),
            k=k, radius=radius))


def _ball_query_batched(mask_points_list, cropped_list, k, radius):
    """Ragged per-mask ball queries, grouped by power-of-two size buckets.

    Masks in one frame span orders of magnitude in (P, S); padding them all
    to the global max costs ~30x the useful distance work (the reason the
    parity A/B never finished at the reference radius). Grouping by the
    (P_pad, S_pad) bucket keeps padding waste < 4x, and the pow2 bucketing
    of all three dims (batch min 4) bounds distinct device-kernel shapes to
    O(log^3) with small constants across a whole scene.
    """
    n = len(mask_points_list)
    p_out = max(len(m) for m in mask_points_list)
    out = np.full((n, p_out, k), -1, dtype=np.int32)
    groups: Dict[Tuple[int, int], List[int]] = {}
    for i, (mp, cp) in enumerate(zip(mask_points_list, cropped_list)):
        key = (_pow2(len(mp), 6), _pow2(len(cp), 8))
        groups.setdefault(key, []).append(i)
    for (p_pad, s_pad), idxs in sorted(groups.items()):
        b = _pow2(len(idxs), 2)
        q = np.zeros((b, p_pad, 3), dtype=np.float32)
        c = np.zeros((b, s_pad, 3), dtype=np.float32)
        ql = np.zeros(b, dtype=np.int32)
        cl = np.zeros(b, dtype=np.int32)
        for j, i in enumerate(idxs):
            mp, cp = mask_points_list[i], cropped_list[i]
            q[j, : len(mp)] = mp
            c[j, : len(cp)] = cp
            ql[j], cl[j] = len(mp), len(cp)
        nb = _ball_query_group(q, c, ql, cl, k, radius)
        for j, i in enumerate(idxs):
            pl = len(mask_points_list[i])
            out[i, :pl] = nb[j, :pl]
    return out


def frame_backprojection_exact(
    scene_points: np.ndarray,  # (N, 3)
    depth: np.ndarray,  # (H, W) metres
    seg: np.ndarray,  # (H, W) int
    intrinsics: np.ndarray,
    cam_to_world: np.ndarray,
    *,
    distance_threshold: float = 0.01,
    depth_trunc: float = 20.0,
    few_points_threshold: int = 25,
    coverage_threshold: float = 0.3,
    k_neighbors: int = 20,
    denoise_eps: float = 0.04,
    denoise_min_points: int = 4,
) -> Dict[int, np.ndarray]:
    """One frame's mask -> scene-point-id sets, reference semantics.

    Returns {mask_id: sorted unique scene point ids} for masks that pass
    the few-points and coverage filters (mask_backprojection.py:70-151).
    """
    if not np.all(np.isfinite(cam_to_world)):
        return {}
    view_points, depth_ok = _frame_view_points(depth, intrinsics, cam_to_world,
                                               depth_trunc)
    seg_flat = seg.reshape(-1)
    candidates: List[Tuple[int, np.ndarray, np.ndarray, np.ndarray]] = []
    for mask_id in np.unique(seg_flat):
        if mask_id == 0:
            continue
        mask_points = view_points[seg_flat[depth_ok] == mask_id]
        if len(mask_points) < few_points_threshold:
            continue
        mask_points = voxel_downsample_np(mask_points, distance_threshold)
        kept = denoise_mask_points(mask_points, eps=denoise_eps,
                                   min_points=denoise_min_points)
        mask_points = mask_points[kept]
        if len(mask_points) < few_points_threshold:
            continue
        lo, hi = mask_points.min(axis=0), mask_points.max(axis=0)
        sel = np.nonzero(np.all((scene_points > lo) & (scene_points < hi), axis=1))[0]
        candidates.append((int(mask_id), mask_points, scene_points[sel], sel))
    if not candidates:
        return {}

    neighbors = _ball_query_batched([c[1] for c in candidates],
                                    [c[2] for c in candidates],
                                    k_neighbors, distance_threshold)
    mask_info: Dict[int, np.ndarray] = {}
    for i, (mask_id, mp, _, sel) in enumerate(candidates):
        nb = neighbors[i, :len(mp)]
        valid_nb = nb >= 0
        coverage = np.any(valid_nb, axis=1).mean() if len(mp) else 0.0
        if coverage < coverage_threshold:
            continue
        local = np.unique(nb[valid_nb])
        mask_info[mask_id] = np.sort(sel[local])
    return mask_info


def associate_scene_exact(tensors, cfg, k_max: int = 127) -> SceneAssociation:
    """Exact-parity SceneAssociation over all frames (host loop).

    Produces the same tensor bundle the dense path emits so the graph,
    clustering, and postprocess stages run unchanged: ascending-id
    overwrite order, shared-point zeroing into boundary, and first/last
    claim ids per point (construction.py:46-62).
    """
    scene_points = np.asarray(tensors.scene_points, dtype=np.float64)
    f = len(tensors.frame_ids)
    n = len(scene_points)
    mop = np.zeros((f, n), dtype=np.int32)
    first = np.zeros((f, n), dtype=np.int32)
    last = np.zeros((f, n), dtype=np.int32)
    point_visible = np.zeros((f, n), dtype=bool)
    mask_valid = np.zeros((f, k_max + 1), dtype=bool)
    boundary = np.zeros(n, dtype=bool)

    for fi in range(f):
        if not tensors.frame_valid[fi]:
            continue
        mask_info = frame_backprojection_exact(
            scene_points,
            np.asarray(tensors.depths[fi]),
            np.asarray(tensors.segmentations[fi]),
            np.asarray(tensors.intrinsics[fi]),
            np.asarray(tensors.cam_to_world[fi]),
            distance_threshold=cfg.distance_threshold,
            depth_trunc=cfg.depth_trunc,
            few_points_threshold=cfg.few_points_threshold,
            coverage_threshold=cfg.coverage_threshold,
            denoise_eps=cfg.denoise_eps,
            denoise_min_points=cfg.denoise_min_points,
        )
        if not mask_info:
            continue
        frame_boundary = np.zeros(n, dtype=bool)
        appeared = np.zeros(n, dtype=bool)
        for mask_id in sorted(mask_info):
            if mask_id > k_max:
                continue
            pts = mask_info[mask_id]
            frame_boundary[pts] |= appeared[pts]
            mop[fi, pts] = mask_id
            first[fi, pts] = np.where(first[fi, pts] > 0,
                                      np.minimum(first[fi, pts], mask_id), mask_id)
            last[fi, pts] = np.maximum(last[fi, pts], mask_id)
            appeared[pts] = True
            point_visible[fi, pts] = True
            mask_valid[fi, mask_id] = True
        mop[fi, frame_boundary] = 0
        boundary |= frame_boundary

    # the dense path emits int16 claim planes (mask ids <= k_max + 1 fit
    # with headroom); the parity path matches so downstream consumers see
    # one contract
    return SceneAssociation(
        mask_of_point=jnp.asarray(mop),
        first_id=jnp.asarray(first.astype(np.int16)),
        last_id=jnp.asarray(last.astype(np.int16)),
        point_visible=jnp.asarray(point_visible),
        boundary=jnp.asarray(boundary),
        mask_valid=jnp.asarray(mask_valid),
    )
