"""Mask-graph statistics as one MXU matmul.

The reference computes, for every mask, a per-frame histogram of which other
masks its points fall into — a serial numpy bincount loop over all masks
(reference graph/construction.py:98-158, "hot loop 2"). The key observation
is that every quantity that loop produces is a slice of one co-occurrence
matrix:

    c[m, m'] = #points of mask m (minus global boundary points)
               that carry mask id m' in frame(m')

which is exactly ``c = A_tilde^T @ W`` for two {0,1} matrices over points:
A_tilde[p, m] = "p is a non-boundary point of m", W[p, m'] = "p carries id
of m' in frame(m')". On TPU this is a counting matmul (ops/counting.py:
bf16 operands + f32 accumulation, or int8 + s32 under
``count_dtype="int8"`` — both bit-exact for 0/1 operands) so the entire
mask-statistics pass rides the systolic array. From c:

- visible-count per (mask, frame):   n_vis[m, j] = sum of c[m, :] over
  frame j's contiguous column range (masks within a frame are disjoint,
  construction.py:24; the ranges are the same slices the segmented argmax
  walks, so n_vis falls out of that pass as a VPU reduction — no f32
  matmul of the count matrix, whose entries exceed every narrow operand
  encoding)
- total valid points per mask:       n_tot = diag(c)
- "contained-by" top mask per frame: segmented argmax of c over each
  frame's masks (construction.py:122-128)
- undersegmentation verdicts and their undo (construction.py:132,163-169)
  become boolean tensor algebra.

The observer-count percentile schedule (construction.py:80-96) is computed
from an exact integer histogram of the O(M^2) observer matrix (counts are
bounded by the frame count, so ~F compare-and-count passes replace a full
M^2 sort); order statistics read off the cumulative histogram are identical
to indexing the sorted array, and only the (F+1,)-sized histogram ever
leaves the device.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from maskclustering_tpu.ops import counting


class MaskTable(NamedTuple):
    """Host-side compact index of valid masks, padded to a static M_pad.

    Padding entries have frame = F (out of range) and id = -1 so they can
    never match a point. Masks are ordered by (frame, id) — ascending and
    contiguous per frame, which the segmented argmax relies on.
    """

    frame: np.ndarray  # (M_pad,) int32
    mask_id: np.ndarray  # (M_pad,) int32, -1 for padding
    valid: np.ndarray  # (M_pad,) bool
    num_masks: int
    num_frames: int
    k_max: int

    @property
    def m_pad(self) -> int:
        return int(self.frame.shape[0])


def build_mask_table(mask_valid: np.ndarray, pad_multiple: int = 256) -> MaskTable:
    """Compact (frame, id) table of valid masks from (F, K_max+1) validity.

    M_pad is a GEOMETRIC bucket of the valid-mask count (same
    two-significant-bit ladder as the F/N pads): every (M_pad,)- and
    (M_pad, M_pad)-shaped stage downstream (graph stats, clustering,
    postprocess claims/assign) compiles per distinct M_pad, and with
    linear 256-rounding nearly every real scene hit a fresh value —
    ~25-40 s of recompile per scene in a mixed-size sweep.
    """
    from maskclustering_tpu.utils.compile_cache import (bucket_size,
                                                        record_shape_bucket)

    mask_valid = np.asarray(mask_valid)
    f_idx, k_idx = np.nonzero(mask_valid)
    num = len(f_idx)
    m_pad = bucket_size(num, pad_multiple)
    record_shape_bucket("masks", m_pad)
    frame = np.full(m_pad, mask_valid.shape[0], dtype=np.int32)
    mask_id = np.full(m_pad, -1, dtype=np.int32)
    frame[:num] = f_idx
    mask_id[:num] = k_idx
    valid = np.zeros(m_pad, dtype=bool)
    valid[:num] = True
    return MaskTable(frame=frame, mask_id=mask_id, valid=valid, num_masks=num,
                     num_frames=int(mask_valid.shape[0]), k_max=int(mask_valid.shape[1]) - 1)


class GraphStats(NamedTuple):
    """Everything the clustering stage needs, all (M_pad, ...) device arrays."""

    visible: jnp.ndarray  # (M_pad, F) bool — reference visible_frames (post-undo)
    contained: jnp.ndarray  # (M_pad, M_pad) bool — reference contained_masks (post-undo)
    undersegment: jnp.ndarray  # (M_pad,) bool
    n_tot: jnp.ndarray  # (M_pad,) f32 valid-point count per mask
    observer_hist: jnp.ndarray  # (F+1,) int32: histogram of observer counts
    # (counts are ints in [0, F]; bin v = #(mask, mask) pairs with v common
    # visible frames — the full M_pad^2 matrix including zero rows)


def _cooccurrence(mask_of_point: jnp.ndarray, boundary: jnp.ndarray,
                  mask_frame: jnp.ndarray, mask_id: jnp.ndarray, point_chunk: int,
                  count_dtype: str = "bf16"):
    """c[m, m'] via chunked counting matmuls (ops/counting.count_dot).

    mask_of_point: (F, N) int32; boundary: (N,) bool. The chunk results
    accumulate in the encoding's exact accumulator dtype (f32 or s32) and
    the final c converts to f32 — exact for any count below 2^24, so both
    encodings return identical arrays.

    Point-sharded meshes (parallel/mesh.py "point" axis): ``mask_of_point``
    arrives with N sharded, the contraction dimension of every chunk
    matmul — GSPMD computes each shard's partial count and psums the
    (M_pad, M_pad) accumulator over the ``point`` axis (the SNIPPETS
    partition-rule pattern: a contraction over a sharded dim is partial
    results + all-reduce). Partial-sum order cannot move a byte: the
    summands are exact integers and both accumulators (f32 below 2^24,
    s32 below 2^31) are associative on them, which is why the
    sharded-vs-unsharded byte-identity pin holds for BOTH count_dtype
    encodings (tests/test_point_sharding.py).
    """
    f, n = mask_of_point.shape
    m_pad = mask_frame.shape[0]
    n_chunks = max(1, -(-n // point_chunk))
    n_padded = n_chunks * point_chunk
    mop = jnp.pad(mask_of_point, ((0, 0), (0, n_padded - n)))  # pad points with id 0
    bnd = jnp.pad(boundary, (0, n_padded - n), constant_values=True)
    # guard the frame gather for padding entries (frame == F)
    safe_frame = jnp.minimum(mask_frame, f - 1)
    acc_dtype = counting.accumulator_dtype(count_dtype)

    def body(carry, pchunk_start):
        c_acc, ntot_acc = carry
        mc = jax.lax.dynamic_slice(mop, (0, pchunk_start), (f, point_chunk))  # (F, Nc)
        bc = jax.lax.dynamic_slice(bnd, (pchunk_start,), (point_chunk,))
        # (Nc, M_pad): does point p carry mask m's id in m's frame?
        ids = mc[safe_frame, :].T  # (Nc, M_pad)
        w_right = (ids == mask_id[None, :])
        w_left = w_right & ~bc[:, None]
        cw = counting.count_dot(w_left.T, w_right, count_dtype=count_dtype,
                                out_dtype=None)
        return (c_acc + cw, ntot_acc + jnp.sum(w_left, axis=0).astype(jnp.float32)), None

    init = (jnp.zeros((m_pad, m_pad), acc_dtype), jnp.zeros((m_pad,), jnp.float32))
    (c, n_tot), _ = jax.lax.scan(body, init, jnp.arange(n_chunks) * point_chunk)
    return c.astype(jnp.float32), n_tot


@functools.partial(jax.jit, static_argnames=("k_max", "point_chunk", "mask_visible_threshold",
                                             "contained_threshold", "undersegment_filter_threshold",
                                             "big_mask_point_count", "count_dtype"))
def compute_graph_stats(
    mask_of_point: jnp.ndarray,  # (F, N) int32, boundary-zeroed
    boundary: jnp.ndarray,  # (N,) bool global boundary points
    mask_frame: jnp.ndarray,  # (M_pad,) int32
    mask_id: jnp.ndarray,  # (M_pad,) int32
    mask_active: jnp.ndarray,  # (M_pad,) bool
    *,
    k_max: int = 127,
    point_chunk: int = 8192,
    mask_visible_threshold: float = 0.3,
    contained_threshold: float = 0.8,
    undersegment_filter_threshold: float = 0.3,
    big_mask_point_count: int = 500,
    count_dtype: str = "bf16",
) -> GraphStats:
    f, n = mask_of_point.shape
    m_pad = mask_frame.shape[0]

    c, n_tot = _cooccurrence(mask_of_point, boundary, mask_frame, mask_id,
                             point_chunk, count_dtype)

    # frame one-hot of each mask slot, in the counting operand dtype (it
    # only feeds counting contractions below; padding has frame == F so
    # its row is all-zero)
    frame_onehot = (mask_frame[:, None] == jnp.arange(f)[None, :]).astype(
        counting.operand_dtype(count_dtype))

    # ---- segmented max + sum over each frame's masks ----
    cmax, top_global, n_vis = frame_segment_stats(c, mask_frame, f, k_max)

    # ---- visibility / containment / undersegmentation logic ----
    safe_tot = jnp.maximum(n_tot, 1.0)[:, None]
    vis_ratio = n_vis / safe_tot
    visible_test = ((vis_ratio >= mask_visible_threshold) | (n_vis >= big_mask_point_count)) \
        & (n_vis > 0) & mask_active[:, None]
    contained_ratio = cmax / jnp.maximum(n_vis, 1.0)
    passes = contained_ratio > contained_threshold
    visible = visible_test & passes  # reference sets visible_frame only on pass
    split = visible_test & ~passes
    visible_num = jnp.sum(visible_test, axis=1)
    split_num = jnp.sum(split, axis=1)
    undersegment = mask_active & (
        (visible_num == 0)
        | (split_num > undersegment_filter_threshold * visible_num)
    )

    # contained[m, m*] = 1 where m* is the argmax mask of a visible frame
    rows = jnp.broadcast_to(jnp.arange(m_pad)[:, None], (m_pad, f))
    contained = jnp.zeros((m_pad, m_pad), dtype=bool)
    safe_top = jnp.where(visible, top_global, m_pad)  # m_pad index dropped
    contained = contained.at[rows.reshape(-1), safe_top.reshape(-1)].set(True, mode="drop")

    # ---- undo undersegmented observers (construction.py:163-169) ----
    u_cols = undersegment[None, :] & contained  # supporters of undersegmented masks
    zap = counting.count_dot(u_cols, frame_onehot, count_dtype=count_dtype) > 0
    visible = visible & ~zap
    contained = contained & ~undersegment[None, :]

    # ---- observer-count distribution for the percentile schedule ----
    # Observer counts are exact small integers <= F, so an exact histogram
    # replaces sorting the M_pad^2 matrix: ~F/8 fused compare-and-count
    # passes over the matrix instead of an O(M^2 log M^2) sort, and order
    # statistics from the cumulative histogram equal sorted-array indexing.
    # The fractional percentile interpolation runs on host in float64
    # (observer_schedule) so thresholds match np.percentile exactly — an
    # f32 lerp can land epsilon above an integer count and flip an
    # `observers >= threshold` decision.
    observers = counting.count_dot(visible, visible.T, count_dtype=count_dtype)
    observer_hist = observer_histogram(observers, f + 1)

    return GraphStats(visible=visible, contained=contained, undersegment=undersegment,
                      n_tot=n_tot, observer_hist=observer_hist)


def observer_histogram(observers: jnp.ndarray, nbins: int) -> jnp.ndarray:
    """Exact integer histogram of an (M, M) observer-count matrix.

    Counts are small integers <= F, so ~F/8 fused compare-and-count
    passes over the matrix replace an O(M^2 log M^2) sort; order
    statistics read off the cumulative histogram equal sorted-array
    indexing. Shared by ``compute_graph_stats`` and the streaming
    re-cluster program (models/streaming.py), which computes the same
    percentile schedule over its accumulated visibility matrix.
    """
    obs_flat = observers.reshape(-1)
    pad_bins = -(-nbins // 8) * 8
    bin_vals = jnp.arange(pad_bins, dtype=jnp.float32).reshape(-1, 8)

    def hist_chunk(_, vals):  # (8,) bin values; compare+count fuses in XLA
        return None, jnp.sum(obs_flat[None, :] == vals[:, None], axis=1)

    _, hist8 = jax.lax.scan(hist_chunk, None, bin_vals)
    return hist8.reshape(-1)[:nbins].astype(jnp.int32)


def frame_segment_stats(c: jnp.ndarray, mask_frame: jnp.ndarray, f: int,
                        k_max: int):
    """Per-frame segmented (max, argmax, sum) over a count matrix's mask
    columns: ``(cmax, top_global, n_vis)``, each (rows, F).

    Table columns are sorted by (frame, id), so each frame's masks occupy
    a CONTIGUOUS column range [starts[j], starts[j+1]): the segmented max
    is F dynamic slices of width k_max — sequential reads at HBM speed —
    instead of an (rows * F * k_max)-element random gather (~1 s/scene
    at ScanNet shape, see PROFILE.md's gather cost). Ties resolve to the
    lowest mask id in both formulations (columns ascend by id). The same
    slices yield n_vis (per-(row, frame) visible counts — masks of a
    frame are disjoint) as a zero-masked row sum, replacing the old
    ``c @ frame_onehot`` f32 matmul: c's entries are counts up to N, too
    wide for any narrow MXU operand encoding, and the slice reduction is
    O(rows * M) reads instead of O(rows * M * F) MACs. ``top_global`` is
    the argmax COLUMN index (the (frame, id)-sorted slot). Shared by
    ``compute_graph_stats`` and the streaming merge program
    (models/streaming.py), whose cross-term rows walk the same chunk
    columns — one copy of the overrun-guard/valid-column semantics.
    """
    rows = c.shape[0]
    starts = jnp.searchsorted(mask_frame, jnp.arange(f + 1, dtype=jnp.int32)
                              ).astype(jnp.int32)  # padding has frame == F
    c_ext = jnp.concatenate(
        [c, jnp.full((rows, k_max), -1.0)], axis=1)  # slice overrun guard

    def frame_max(j):
        sl = jax.lax.dynamic_slice(c_ext, (0, starts[j]), (rows, k_max))
        valid_col = jnp.arange(k_max) < (starts[j + 1] - starts[j])
        slm = jnp.where(valid_col[None, :], sl, -1.0)
        return (jnp.max(slm, axis=1),
                starts[j] + jnp.argmax(slm, axis=1).astype(jnp.int32),
                jnp.sum(jnp.where(valid_col[None, :], sl, 0.0), axis=1))

    cmax, top, n_vis = jax.lax.map(frame_max, jnp.arange(f))  # (F, rows) x3
    return cmax.T, top.T, n_vis.T


def observer_schedule_device(observer_hist: jnp.ndarray,
                             max_len: int = 20) -> jnp.ndarray:
    """Jittable (f32) observer-percentile schedule for the fused device path.

    Same semantics as `observer_schedule` (reference construction.py:80-96)
    but computed in f32 on device so the whole pipeline can stay inside one
    jit (the multi-chip fused step, parallel/sharded.py). Entries past the
    reference's early-termination point (percentile < 50 and value <= 1)
    become +inf, which makes those clustering iterations inert. Host parity
    runs use `observer_schedule` (float64 interpolation).

    ``observer_hist``: integer counts per observer value 0..len-1; the
    order statistic at rank k is the first value whose cumulative count
    exceeds k — identical to indexing the sorted flat matrix.
    """
    hist = observer_hist.astype(jnp.int32)
    cum = jnp.cumsum(hist)  # int32: safe to M_pad^2 < 2^31 (M_pad <= ~46k)
    total = cum[-1]
    cnt = total - hist[0]  # positive observer pairs
    qs_i = jnp.arange(95, -5, -5, dtype=jnp.int32)[:max_len]
    qs = qs_i.astype(jnp.float32)
    # rank position = (total - cnt) + (cnt - 1) * q / 100, split into an
    # exact integer part and a fractional remainder so int32 cannot
    # overflow at M_pad^2 scale (cnt*q would; split cnt-1 = 100*d + r:
    # (cnt-1)*q/100 = d*q + r*q/100).
    cm1 = jnp.maximum(cnt - 1, 0)
    d, r = cm1 // 100, cm1 % 100
    rq = r * qs_i  # <= 99*95, exact
    lo = (total - cnt) + d * qs_i + rq // 100
    frac = (rq % 100).astype(jnp.float32) / 100.0
    lo = jnp.clip(lo, 0, total - 1)
    hi = jnp.minimum(lo + 1, total - 1)
    v_lo = jnp.searchsorted(cum, lo + 1, side="left").astype(jnp.float32)
    v_hi = jnp.searchsorted(cum, hi + 1, side="left").astype(jnp.float32)
    interp = v_lo * (1.0 - frac) + jnp.where(hi > lo, v_hi, v_lo) * frac
    le1 = interp <= 1.0
    clipped = jnp.where(le1, 1.0, interp)
    dead = (le1 & (qs < 50)) | (cnt == 0)
    stopped = jnp.cumsum(dead.astype(jnp.int32)) > 0
    return jnp.where(stopped, jnp.inf, clipped)


def observer_schedule(observer_hist, max_len: int = 20) -> np.ndarray:
    """Observer-count percentile schedule from the observer histogram.

    Reference semantics (construction.py:80-96): np.percentile (linear
    interpolation, float64) of the positive observer counts at 95..0 step
    -5; a value <= 1 becomes 1 while the percentile is >= 50 and terminates
    the schedule once below 50. Padded to `max_len` with +inf (an inert
    clustering iteration merges nothing).

    Only the (F+1,)-sized histogram crosses the device->host boundary; the
    order statistics it yields are exactly the sorted flat matrix's values.
    """
    from maskclustering_tpu import obs

    obs.count_transfer("d2h", getattr(observer_hist, "nbytes", 0), "graph")
    hist = np.asarray(observer_hist, dtype=np.int64)
    cum = np.cumsum(hist)
    total = int(cum[-1])
    cnt_pos = total - int(hist[0])
    out = []
    if cnt_pos > 0:
        qs = list(range(95, -5, -5))
        pos = (total - cnt_pos) + (cnt_pos - 1) * (np.asarray(qs) / 100.0)  # float64
        lo = np.minimum(np.floor(pos).astype(np.int64), total - 1)
        hi = np.minimum(lo + 1, total - 1)
        v_lo = np.searchsorted(cum, lo + 1, side="left").astype(np.float64)
        v_hi = np.searchsorted(cum, hi + 1, side="left").astype(np.float64)
        frac = pos - lo
        interp = v_lo * (1.0 - frac) + np.where(hi > lo, v_hi, v_lo) * frac
        for q, val in zip(qs, interp):
            val = float(val)
            if val <= 1:
                if q < 50:
                    break
                val = 1.0
            out.append(val)
    sched = np.full(max_len, np.inf, dtype=np.float32)
    sched[: len(out)] = out[:max_len]
    return sched
