"""Family 3: the runtime transfer sanitizer (opt-in, zero-cost when off).

The AST lint sees syntactic syncs; it cannot see an implicit transfer
born inside a library call — an eager op chain mixing a Python scalar
into a device computation (the io/feed decode used to upload its scale
constant per scene exactly this way), a stray ``__array__`` on a device
value, a debug print. This module arms ``jax.transfer_guard("disallow")``
around the DEVICE PHASE of every scene (``run_scene_device``), so any
implicit transfer becomes a hard ``XlaRuntimeError`` at the offending
line — on CPU, in CI, before a chip ever sees it.

Opt-in via ``run.py --transfer-guard`` or ``MCT_TRANSFER_GUARD=1``; the
single sanctioned host pull of the pipeline (the mask table — the
assignment pull moved on device with the device-resident post-process)
opens a ``sanctioned_pull`` window that restores ``allow`` — the guard
verifies the 1-sync contract's COMPLEMENT: nothing else crosses.

jax's transfer guard is thread-local, so guarding the device phase on the
dispatch thread never constrains the overlapped executor's host-tail
worker (whose claim drains are sanctioned by design).

Off (the default) both context managers are a shared null context: no
jax import cost at call time, no per-scene overhead.
"""

from __future__ import annotations

import contextlib
import os
from typing import Iterator, Optional

ENV_FLAG = "MCT_TRANSFER_GUARD"

_armed: Optional[bool] = None  # None -> the environment decides


def arm(on: Optional[bool]) -> None:
    """Explicitly enable/disable the guard (``None`` defers to the env)."""
    global _armed
    _armed = on


def enabled() -> bool:
    if _armed is not None:
        return _armed
    return os.environ.get(ENV_FLAG, "").strip().lower() in ("1", "true",
                                                            "on", "yes")


@contextlib.contextmanager
def device_phase_guard() -> Iterator[None]:
    """``jax.transfer_guard("disallow")`` around a device phase when armed."""
    if not enabled():
        yield
        return
    import jax

    with jax.transfer_guard("disallow"):
        yield


@contextlib.contextmanager
def sanctioned_pull(what: str) -> Iterator[None]:
    """A declared host-pull window inside a guarded device phase.

    ``what`` names the pull for error context only; the AST lint
    recognizes this context manager as a sanctioned seam, so runtime
    sanction and static sanction stay one vocabulary.
    """
    del what  # documentation + lint marker; the guard needs no label
    if not enabled():
        yield
        return
    import jax

    with jax.transfer_guard("allow"):
        yield
