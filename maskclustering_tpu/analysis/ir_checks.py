"""Family 1: IR invariants read from the AOT-lowered fused step.

Reuses the obs/cost.py lowering seam — ``build_fused_step`` over CPU
virtual devices, censuses parsed from the StableHLO/optimized-HLO text —
so the checks inspect the program production runs, not a lookalike, and
need no chip and no new compile machinery. Four invariants:

- **counting-dtype policy** (generalizes DTYPE_CENSUS.md from a one-shot
  report into pass/fail): every dot class in the lowered module is either
  the configured counting class (``bf16xbf16->f32`` / ``i8xi8->i32``) or
  a member of the audited stays-wide f32 set, whose size is pinned
  (``EXPECTED_WIDE_DOTS``) so a counting dispatch that silently regresses
  to a raw f32 dot GROWS the wide census and fails; nothing may widen to
  f64; the (F, N) claim-plane outputs stay s16.
- **host-transfer census**: the compiled fused step contains zero
  mid-program host crossings (send/recv/infeed/outfeed/host callbacks)
  across the divisor lattice of 8 — so the only device->host syncs are
  the orchestrated pulls, whose source sites are counted by
  ``check_source_sync_sites`` (exactly 2 in ``run_scene_device``, the
  PR-3 contract).
- **donation effectiveness**: every input ``cfg.donate_buffers`` donates
  must carry a ``tf.aliasing_output`` marker in the lowered module. A
  donation XLA could not alias leaves NO marker (jax drops it with a
  warning this repo suppresses) — that silent waste is exactly what this
  check surfaces; known-unaliasable cases live in the baseline with their
  justification instead of being invisible.
- **collective-payload budget** (pins MESH_BENCH.md's settled numbers
  statically): pure scene-DP moves <= 2 bytes (the two 1-byte ``pred[]``
  while-predicates); frame-sharded meshes stay within a declared envelope
  at the canonical analyzer shape.
"""

from __future__ import annotations

import ast
import math
import os
import re
from typing import Dict, List, Optional, Sequence, Tuple

from maskclustering_tpu.analysis.findings import Finding, make_id

# ---------------------------------------------------------------------------
# policy constants (the contracts, in one place)
# ---------------------------------------------------------------------------

# canonical analyzer shape: tiny enough that the full divisor lattice of 8
# AOT-compiles in ~12 s on CPU, large enough that every counting dot and
# collective of the production program appears in the lowering
CANONICAL_SHAPE = dict(frames=8, points=1024, image_hw=(24, 32), k_max=7)

# the full divisor lattice of 8: every (scene, frame) factorization
LATTICE: Tuple[Tuple[int, int], ...] = ((1, 8), (2, 4), (4, 2), (8, 1))

# the point-sharded lattice cell the gate lowers by default: one 3-axis
# (scene, frame, point) mesh is enough to pin the psum-over-point program
# shape (the full 3-axis divisor sweep runs execution-level, slow-marked,
# in tests/test_point_sharding.py). Kept to ONE mesh so the tier-1
# conftest sweep pays a single extra AOT compile.
POINT_LATTICE: Tuple[Tuple[int, int, int], ...] = ((1, 2, 4),)
FULL_LATTICE: Tuple[Tuple[int, ...], ...] = LATTICE + POINT_LATTICE

# counting-contraction operand class per cfg.count_dtype (ops/counting.py)
COUNTING_DOT_CLASS = {"bf16": "bf16xbf16->f32", "int8": "i8xi8->i32"}
# the audited stays-wide set: f32 projection/geometry matmuls only
WIDE_DOT_CLASSES = frozenset({"f32xf32->f32"})
# ...and its pinned size (DTYPE_CENSUS.md's per-site table): a counting
# dispatch regressing to a raw f32 dot grows this census and fails here
EXPECTED_WIDE_DOTS = 3

# run_scene_device's host-sync contract (models/pipeline.py): exactly ONE
# mid-program crossing — the mask-table bucket pull. The assignment pull
# (historical sync 2/2) moved on device with the device-resident
# post-process (models/postprocess_device.py, PR 8)
EXPECTED_HOST_SYNCS = 1

# scene-DP collective budget: two 1-byte pred[] while-loop predicates
# (MESH_BENCH.md "Pure scene-DP moves 2 bytes across chips")
SCENE_DP_ICI_BUDGET_BYTES = 2.0
# frame-sharded envelope at CANONICAL_SHAPE: measured 92,458 B (12
# all-gathers + while predicates); 128 KiB leaves ~40% headroom for
# benign layout drift while a new data collective (~M_pad*F bytes at
# minimum) still lands far outside it
FRAME_SHARDED_ICI_BUDGET_BYTES = 128.0 * 1024
# point-sharded envelope at CANONICAL_SHAPE (MESH_BENCH.md point-axis
# census): the psum-over-point partial counts + routing gathers measured
# 46-179 KB across the 3-axis lattice cells (1x2x4 = 162,660 B); 256 KiB
# leaves ~45% headroom while the pathology this gate exists to catch —
# the ~100 MB estimate-spacing all-to-all a naive point constraint
# produced — lands 400x outside it
POINT_SHARDED_ICI_BUDGET_BYTES = 256.0 * 1024

# donated fused-step params: depths (1) and segs (2) — parallel/sharded.py
# build_fused_step donate_argnums; utils/donation.py documents why their
# aliasing so rarely materializes
FUSED_DONATE_ARGNUMS = (1, 2)
# the postprocess group-counts kernel donates first/last (args 0, 1)
GROUPCOUNTS_DONATE_ARGNUMS = (0, 1)

# claim-plane outputs that must stay s16 (PR-4 narrowing)
CLAIM_PLANE_OUTPUTS = ("first_id", "last_id")

# mid-program host-crossing instructions in optimized HLO; the callback
# patterns are jax's host-callback custom-call targets (io_callback /
# pure_callback / debug prints) — each one is a hidden per-dispatch sync
# result types may be tuples with spaces — `%s = (f32[8], token[]) send(`
# — so the type alternation mirrors obs/cost.py's _op_pattern
_HLO_TYPE = r"(?:\([^=]*?\)|\S+)"
_HOST_TRANSFER_RES = {
    "send": re.compile(r"=\s*" + _HLO_TYPE + r"\s+send(?:-start)?\("),
    "recv": re.compile(r"=\s*" + _HLO_TYPE + r"\s+recv(?:-start)?\("),
    "infeed": re.compile(r"=\s*" + _HLO_TYPE + r"\s+infeed\("),
    "outfeed": re.compile(r"=\s*" + _HLO_TYPE + r"\s+outfeed\("),
    "host-callback": re.compile(
        r"custom-call[^\n]*(?:python_cpu_callback|host_callback)"),
}

_RESULT_DTYPE_RE = (
    r"tensor<[0-9x]*x([a-z]+[0-9]+)>\s*\{[^}]*jax\.result_info = \"\.%s\"")


# ---------------------------------------------------------------------------
# pure text/census checks (unit-testable without jax)
# ---------------------------------------------------------------------------


def check_dot_classes(dots: Dict[str, Dict[str, float]], count_dtype: str,
                      label: str) -> List[Finding]:
    """Dot-class conformance of one lowering's census (obs.cost.dot_census)."""
    out: List[Finding] = []
    counting_class = COUNTING_DOT_CLASS[count_dtype]
    for cls, row in sorted(dots.items()):
        if cls == counting_class or cls in WIDE_DOT_CLASSES:
            continue
        out.append(Finding(
            id=make_id("IR.DTYPE.CLASS", label, cls),
            check="IR.DTYPE.CLASS", family="ir",
            message=f"{label}: dot class {cls} (x{int(row['count'])}) is "
                    f"neither the {count_dtype!r} counting class "
                    f"({counting_class}) nor in the audited wide set"))
    wide = sum(int(dots[c]["count"]) for c in dots if c in WIDE_DOT_CLASSES)
    if wide != EXPECTED_WIDE_DOTS:
        out.append(Finding(
            id=make_id("IR.DTYPE.WIDE", label),
            check="IR.DTYPE.WIDE", family="ir",
            message=f"{label}: {wide} wide f32 dot(s), expected "
                    f"{EXPECTED_WIDE_DOTS} (the audited projection/geometry "
                    f"set) — a counting contraction regressed to f32, or a "
                    f"new wide matmul needs auditing (DTYPE_CENSUS.md)"))
    return out


def check_no_f64(stablehlo_text: str, label: str) -> List[Finding]:
    if "f64" not in stablehlo_text:
        return []
    n = stablehlo_text.count("xf64")
    return [Finding(
        id=make_id("IR.DTYPE.F64", label),
        check="IR.DTYPE.F64", family="ir",
        message=f"{label}: f64 appeared in the lowered module "
                f"({n} tensor reference(s)) — nothing in the device "
                f"pipeline may widen to f64")]


def check_claim_planes(stablehlo_text: str, label: str) -> List[Finding]:
    """The (F, N) first/last claim-plane outputs must stay s16 (PR 4)."""
    out: List[Finding] = []
    for name in CLAIM_PLANE_OUTPUTS:
        m = re.search(_RESULT_DTYPE_RE % name, stablehlo_text)
        if m is None:
            out.append(Finding(
                id=make_id("IR.DTYPE.PLANE", label, name, "missing"),
                check="IR.DTYPE.PLANE", family="ir",
                message=f"{label}: fused-step output {name!r} not found in "
                        f"the lowered signature — claim-plane contract "
                        f"unverifiable"))
        elif m.group(1) != "i16":
            out.append(Finding(
                id=make_id("IR.DTYPE.PLANE", label, name, m.group(1)),
                check="IR.DTYPE.PLANE", family="ir",
                message=f"{label}: claim plane {name} lowered as "
                        f"{m.group(1)}, must stay i16 (the PR-4 HBM "
                        f"halving)"))
    return out


def check_host_transfers(compiled_text: str, label: str) -> List[Finding]:
    """Zero mid-program host crossings in the compiled fused step."""
    out: List[Finding] = []
    for kind, pat in _HOST_TRANSFER_RES.items():
        n = len(pat.findall(compiled_text))
        if n:
            out.append(Finding(
                id=make_id("IR.SYNC.HLO", label, kind),
                check="IR.SYNC.HLO", family="ir",
                message=f"{label}: compiled step contains {n} {kind} "
                        f"instruction(s) — a mid-program host crossing "
                        f"breaks the 2-sync scene contract"))
    return out


def check_collective_budget(ici_bytes: float,
                            collectives: Dict[str, Dict[str, float]],
                            mesh: Tuple[int, ...], label: str,
                            canonical_shape: bool = True) -> List[Finding]:
    """Scene-DP <= 2 bytes always; frame-sharded within the envelope at
    the canonical shape (budgets are shape-dependent there); point-sharded
    meshes get their own envelope — the psum-over-point partial counts are
    sanctioned traffic, a resharding all-to-all of the (F, N) planes is
    not."""
    f_ax = mesh[1]
    p_ax = mesh[2] if len(mesh) == 3 else 1
    if p_ax > 1:
        if not canonical_shape:
            return []
        if ici_bytes > POINT_SHARDED_ICI_BUDGET_BYTES:
            return [Finding(
                id=make_id("IR.COLLECTIVE.POINT", label),
                check="IR.COLLECTIVE.POINT", family="ir",
                message=f"{label}: point-sharded ICI payload "
                        f"{ici_bytes:.0f} B exceeds the "
                        f"{POINT_SHARDED_ICI_BUDGET_BYTES:.0f} B canonical-"
                        f"shape envelope — a reshard of an N-sized "
                        f"resident joined the fused step (the sanctioned "
                        f"traffic is partial-count psums + small gathers; "
                        f"see MESH_BENCH.md point-axis census)")]
        return []
    if f_ax == 1:
        data_colls = {k: v for k, v in collectives.items()
                      if k != "all-reduce"}
        out: List[Finding] = []
        if data_colls:
            out.append(Finding(
                id=make_id("IR.COLLECTIVE.SCENE_DP", label, "data"),
                check="IR.COLLECTIVE.SCENE_DP", family="ir",
                message=f"{label}: pure scene-DP compiled DATA "
                        f"collective(s) {sorted(data_colls)} — cross-scene "
                        f"traffic appeared on the critical path"))
        if ici_bytes > SCENE_DP_ICI_BUDGET_BYTES:
            out.append(Finding(
                id=make_id("IR.COLLECTIVE.SCENE_DP", label, "bytes"),
                check="IR.COLLECTIVE.SCENE_DP", family="ir",
                message=f"{label}: scene-DP ICI payload {ici_bytes:.0f} B "
                        f"exceeds the {SCENE_DP_ICI_BUDGET_BYTES:.0f} B "
                        f"while-predicate budget (MESH_BENCH.md)"))
        return out
    if not canonical_shape:
        return []
    if ici_bytes > FRAME_SHARDED_ICI_BUDGET_BYTES:
        return [Finding(
            id=make_id("IR.COLLECTIVE.FRAME", label),
            check="IR.COLLECTIVE.FRAME", family="ir",
            message=f"{label}: frame-sharded ICI payload {ici_bytes:.0f} B "
                    f"exceeds the {FRAME_SHARDED_ICI_BUDGET_BYTES:.0f} B "
                    f"canonical-shape envelope — a new collective joined "
                    f"the fused step")]
    return []


def donated_param_aliases(stablehlo_text: str) -> Dict[int, Optional[int]]:
    """%argN -> aliased output index for params carrying donation markers.

    ``tf.aliasing_output = K`` means XLA aliased the donated input to
    output K; ``jax.buffer_donor = true`` (rare) means declared-but-
    unresolved. Params with neither marker are absent from the dict —
    indistinguishable from never-donated, which is the point of the check.
    """
    sig = stablehlo_text[stablehlo_text.index("func.func public @main"):]
    sig = sig[:sig.index(")\n") + 1] if ")\n" in sig else sig
    out: Dict[int, Optional[int]] = {}
    for m in re.finditer(r"%arg(\d+): tensor<[^>]+>\s*(\{[^}]*\})?", sig):
        attrs = m.group(2) or ""
        alias = re.search(r"tf\.aliasing_output = (\d+)", attrs)
        if alias:
            out[int(m.group(1))] = int(alias.group(1))
        elif "jax.buffer_donor" in attrs:
            out[int(m.group(1))] = None
    return out


def check_donation(stablehlo_text: str, donated_args: Sequence[int],
                   label: str) -> List[Finding]:
    """Every donated param must be effectively aliased in the lowering."""
    aliases = donated_param_aliases(stablehlo_text)
    out: List[Finding] = []
    for argnum in donated_args:
        if aliases.get(argnum) is None:
            state = ("declared but unresolved (jax.buffer_donor)"
                     if argnum in aliases else
                     "absent from the lowering (dropped as unusable, or "
                     "the donate wiring was removed)")
            out.append(Finding(
                id=make_id("IR.DONATION", label, f"arg{argnum}"),
                check="IR.DONATION", family="ir",
                message=f"{label}: donated input %arg{argnum} is {state} — "
                        f"no buffer aliasing in the executable"))
    return out


# donate_argnums tuples the source must carry: CPU lowers these donations
# away as unusable (the baselined IR.DONATION findings), so the IR alone
# cannot tell "declared but unaliasable" from "wiring deleted" — this
# source-level check is what makes a DROPPED donation fail the gate
DONATION_WIRING = (
    ("maskclustering_tpu/parallel/sharded.py", (1, 2)),
    ("maskclustering_tpu/models/postprocess_device.py", (0, 1)),
)


def check_donation_wiring(repo_root: str) -> List[Finding]:
    """Every expected ``donate_argnums=(...)`` tuple still exists in source."""
    out: List[Finding] = []
    for rel, expected in DONATION_WIRING:
        path = os.path.join(repo_root, rel)
        found: set = set()
        try:
            with open(path, "r", encoding="utf-8") as f:
                tree = ast.parse(f.read(), filename=path)
        except (OSError, SyntaxError):
            tree = None
        if tree is not None:
            for node in ast.walk(tree):
                if not isinstance(node, ast.keyword) \
                        or node.arg != "donate_argnums":
                    continue
                for sub in ast.walk(node.value):
                    if isinstance(sub, ast.Tuple) and all(
                            isinstance(e, ast.Constant) for e in sub.elts):
                        found.add(tuple(e.value for e in sub.elts))
        if expected not in found:
            out.append(Finding(
                id=make_id("IR.DONATION.WIRING", rel,
                           "-".join(map(str, expected))),
                check="IR.DONATION.WIRING", family="ir",
                message=f"{rel}: donate_argnums={expected} no longer in "
                        f"source — a cfg.donate_buffers donation was "
                        f"dropped (HBM stops recycling at the shape "
                        f"bucket)",
                file=rel))
    return out


def check_source_sync_sites(pipeline_path: str,
                            rel: str = "maskclustering_tpu/models/pipeline.py"
                            ) -> List[Finding]:
    """The source half of the 2-sync contract: ``run_scene_device`` bumps
    ``pipeline.host_sync`` exactly EXPECTED_HOST_SYNCS times."""
    with open(pipeline_path, "r", encoding="utf-8") as f:
        tree = ast.parse(f.read(), filename=pipeline_path)
    # the public wrapper + its guard-wrapped impl are ONE device phase
    phase_fns = ("run_scene_device", "_run_scene_device_impl")
    sites = 0
    anchor = 0
    found = False
    for node in ast.walk(tree):
        if isinstance(node, ast.FunctionDef) and node.name in phase_fns:
            found = True
            anchor = anchor or node.lineno
            sites += sum(
                1 for n in ast.walk(node)
                if isinstance(n, ast.Call) and n.args
                and isinstance(n.args[0], ast.Constant)
                and n.args[0].value == "pipeline.host_sync"
                and isinstance(n.func, ast.Attribute)
                and n.func.attr == "count")
    if not found:
        return [Finding(
            id=make_id("IR.SYNC.SOURCE", "missing"),
            check="IR.SYNC.SOURCE", family="ir",
            message="run_scene_device not found in models/pipeline.py — "
                    "host-sync contract unverifiable", file=rel)]
    if sites == EXPECTED_HOST_SYNCS:
        return []
    return [Finding(
        id=make_id("IR.SYNC.SOURCE", "run_scene_device"),
        check="IR.SYNC.SOURCE", family="ir",
        message=f"run_scene_device carries {sites} pipeline.host_sync "
                f"site(s), contract says exactly {EXPECTED_HOST_SYNCS} "
                f"(mask table + assignment)",
        file=rel, line=anchor)]


# ---------------------------------------------------------------------------
# the driver: lower once per (mesh, dtype), fan the checks over the texts
# ---------------------------------------------------------------------------


def _mesh_label(mesh_shape: Tuple[int, ...]) -> str:
    """SxF / SxFxP label (stdlib mirror of parallel.mesh.mesh_label so a
    pure-AST analysis run never imports jax through this module)."""
    return "x".join(str(int(d)) for d in mesh_shape)


def _lower_fused(mesh_shape: Tuple[int, ...], cfg, shape: Dict):
    """(lowered, label) for the fused step on one lattice mesh."""
    from maskclustering_tpu.parallel.mesh import make_mesh
    from maskclustering_tpu.parallel.sharded import (
        build_fused_step,
        stage_arg_shapes,
    )

    mesh = make_mesh(mesh_shape)
    step = build_fused_step(mesh, cfg, k_max=shape["k_max"],
                            donate=bool(cfg.donate_buffers))
    shapes = stage_arg_shapes(
        "backprojection", scenes=mesh_shape[0], frames=shape["frames"],
        points=shape["points"], image_hw=tuple(shape["image_hw"]),
        k_max=shape["k_max"])
    return step.lower(*shapes)


def _lower_groupcounts(shape: Dict):
    """Lower the donating postprocess group-counts kernel at tiny shapes."""
    import jax
    import jax.numpy as jnp

    from maskclustering_tpu.models.postprocess_device import (
        _mask_group_counts_kernel_donating,
    )

    f, n = shape["frames"], shape["points"]
    k2 = shape["k_max"] + 2
    m_pad = f * shape["k_max"]
    sds = jax.ShapeDtypeStruct
    return _mask_group_counts_kernel_donating.lower(
        sds((f, n), jnp.int16), sds((f, n), jnp.int16),
        sds((n, 128), jnp.bfloat16),
        sds((m_pad,), jnp.int32), sds((m_pad,), jnp.int32),
        sds((m_pad,), jnp.int32), k2=k2, s_pad=128,
        count_dtype="bf16")


def analyze_ir(
    meshes: Sequence[Tuple[int, ...]] = FULL_LATTICE,
    *,
    shape: Optional[Dict] = None,
    cfg=None,
    repo_root: Optional[str] = None,
    lowerings: Optional[Dict[Tuple[int, int], Tuple[str, str]]] = None,
) -> Tuple[List[Finding], List[Dict]]:
    """Run Family 1 end-to-end; returns (findings, JSON-able census rows).

    One fused lowering+compile per mesh under the production config
    (``count_dtype`` default, donation per ``cfg.donate_buffers``), plus a
    lower-only int8 variant on the first mesh for the narrowing A/B, plus
    the donating group-counts kernel. ~15 s of CPU compiles at the
    canonical shape over the full lattice; never materializes data.

    ``lowerings`` maps a mesh to precomputed ``(stablehlo_text,
    compiled_hlo_text)`` of the fused step at ``shape`` under the SAME
    default config — ``obs.cost.observe_costs(..., keep_texts=True)``
    produces them — so one AOT sweep can serve both the cost rows and
    this gate (the tier-1 conftest de-duplication). Meshes not in the
    dict lower here as before.
    """
    from maskclustering_tpu.obs.cost import (
        collective_census,
        default_pipeline_cfg,
        dot_census,
        ensure_cpu_devices,
        ici_bytes,
    )

    shape = dict(CANONICAL_SHAPE) if shape is None else dict(shape)
    canonical = shape == CANONICAL_SHAPE
    if cfg is None:
        cfg = default_pipeline_cfg(
            point_chunk=max(256, shape["points"] // 4))
    n_dev = ensure_cpu_devices(8)
    findings: List[Finding] = []
    rows: List[Dict] = []

    ab_dots: Dict[str, Dict] = {}
    analyzed = 0
    for mesh_shape in meshes:
        if math.prod(mesh_shape) != n_dev:
            # a mesh that does not fit the backend is skipped — but see the
            # IR.MESH backstop below: skipping EVERY mesh must not pass
            continue
        analyzed += 1
        label = f"fused@{_mesh_label(mesh_shape)}"
        pre = (lowerings or {}).get(tuple(mesh_shape))
        if pre is not None:
            stablehlo, compiled_text = pre
        else:
            lowered = _lower_fused(mesh_shape, cfg, shape)
            stablehlo = lowered.as_text()
            compiled_text = lowered.compile().as_text()
        dots = dot_census(stablehlo)
        colls = collective_census(compiled_text)
        ici = ici_bytes(colls)
        findings += check_dot_classes(dots, cfg.count_dtype, label)
        findings += check_no_f64(stablehlo, label)
        findings += check_claim_planes(stablehlo, label)
        findings += check_host_transfers(compiled_text, label)
        findings += check_collective_budget(ici, colls, mesh_shape, label,
                                            canonical_shape=canonical)
        if len(mesh_shape) < 3:
            # the donation marker is a property of (program, backend), not
            # of the mesh factorization: on this CPU gate it is ALWAYS
            # dropped-as-unusable (the four 2-axis labels' baselined
            # concession says exactly that), so a point-mesh instance
            # would only mint another identical suppression.
            # IR.DONATION.WIRING keeps source-level teeth on every mesh.
            findings += check_donation(stablehlo, FUSED_DONATE_ARGNUMS,
                                       label)
        rows.append({"target": label, "mesh": list(mesh_shape),
                     "count_dtype": cfg.count_dtype, "dots": dots,
                     "collectives": colls, "ici_bytes": ici,
                     "fingerprint": shape})
        if not ab_dots:
            ab_dots[cfg.count_dtype] = dots
            other = "int8" if cfg.count_dtype == "bf16" else "bf16"
            lo8 = _lower_fused(mesh_shape, cfg.replace(count_dtype=other),
                               shape)
            ab_dots[other] = dot_census(lo8.as_text())
            findings += check_narrowing_ab(ab_dots, label)

    if analyzed == 0:
        # hard backstop: a --mesh typo (e.g. 4x4 on an 8-device backend)
        # must never turn the fused-step gate silently green — every IR
        # invariant above would be unverified while mct-check exits 0
        findings.append(Finding(
            id=make_id("IR.MESH", "none-analyzed"),
            check="IR.MESH", family="ir",
            message=f"no requested mesh {sorted(set(meshes))} fits the "
                    f"{n_dev}-device backend — zero fused-step lowerings "
                    f"analyzed, the IR invariants are unverified (fix "
                    f"--mesh or the device count)"))

    # the donating group-counts kernel (postprocess_device) — per-scene,
    # mesh-independent
    gc_lowered = _lower_groupcounts(shape)
    findings += check_donation(gc_lowered.as_text(),
                               GROUPCOUNTS_DONATE_ARGNUMS,
                               "post.group_counts")

    root = repo_root or _repo_root()
    pipeline_py = os.path.join(root, "maskclustering_tpu", "models",
                               "pipeline.py")
    if os.path.exists(pipeline_py):
        findings += check_source_sync_sites(pipeline_py)
    findings += check_donation_wiring(root)
    return findings, rows


def check_narrowing_ab(ab_dots: Dict[str, Dict], label: str) -> List[Finding]:
    """The bf16-vs-int8 narrowing A/B: classes that differ between the two
    lowerings are the counting contractions — they must be exactly the two
    counting classes with EQUAL instruction counts, and non-empty."""
    if set(ab_dots) != {"bf16", "int8"}:
        return []
    db, d8 = ab_dots["bf16"], ab_dots["int8"]
    stable = {k for k in db if k in d8 and d8[k] == db[k]}
    narrowed_b = {k: v for k, v in db.items() if k not in stable}
    narrowed_8 = {k: v for k, v in d8.items() if k not in stable}
    cb = COUNTING_DOT_CLASS["bf16"]
    c8 = COUNTING_DOT_CLASS["int8"]
    ok = (set(narrowed_b) == {cb} and set(narrowed_8) == {c8}
          and narrowed_b[cb]["count"] == narrowed_8[c8]["count"]
          and narrowed_b[cb]["count"] > 0)
    if ok:
        return []
    return [Finding(
        id=make_id("IR.DTYPE.NARROW", label),
        check="IR.DTYPE.NARROW", family="ir",
        message=f"{label}: count_dtype A/B narrowing broke — bf16 variant "
                f"classes {sorted(narrowed_b)} vs int8 {sorted(narrowed_8)}; "
                f"every counting contraction must flip between "
                f"{cb} and {c8} with equal counts")]


def _repo_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


def parse_meshes(specs: Sequence[str]) -> List[Tuple[int, int]]:
    """CLI mesh parsing, shared with the cost observatory."""
    from maskclustering_tpu.obs.cost import parse_mesh_specs

    return parse_mesh_specs(specs)
