"""Family 2: AST lint over ``maskclustering_tpu/`` + ``scripts/``.

Four domain checks no generic linter expresses:

- **AST.HOSTSYNC** — unsanctioned host-sync calls (``np.asarray``,
  ``jax.device_get``, ``.item()``, ``.block_until_ready()``, and
  ``float(...)``/``bool(...)`` of a call result) in the device-path
  modules. Sanctioned means the call sits in a ``with`` block whose
  ITEMS declare a pull seam: a ``transfer_guard.sanctioned_pull``
  context, or a span whose name contains ``"pull"`` / that passes a
  ``host_pull`` attr. Body-level markers (a booked ``d2h``, a
  ``host_pull`` attr set later) deliberately do NOT sanction — they
  would blind the lint to a second pull added to the same block; booked
  but unwrapped pulls live in the baseline instead.
  Scope is ``DEVICE_PATH_MODULES`` only — host-side numpy plumbing is not
  a sync hazard, and diagnostics scripts sync on purpose.
- **AST.JITPURITY** — wall-clock/randomness reachable from jitted code:
  module-local reachability from every traced root (functions passed to
  or decorated with ``jax.jit``/``vmap``/``pmap``/``lax.scan`` & co) to a
  ``time.*``/``np.random``/``random``/``datetime.now`` call. Tracing
  bakes the value at compile time — a silent wrong-answer bug.
- **AST.THREADS** — module-level mutable state mutated without a lock in
  thread-reachable code (the PR-3 unlocked-metrics-registry race as the
  motivating pattern): entry points are functions handed to
  ``DaemonFuture``/``threading.Thread`` anywhere in the tree (plus
  ``THREAD_ENTRY_HINTS`` for cross-module dispatch), reachability closes
  over same-module calls, and a mutation counts as guarded only inside a
  ``with <...lock...>`` block.
- **AST.EXCEPT** — bare ``except:`` handlers, which would swallow the
  typed fault classes of ``utils/faults.py`` (``DeviceStallError``
  carries the retry/degradation routing; a bare except eats it).

Inline opt-out: append ``# mct-ok: <CHECK>`` to the offending line (e.g.
``# mct-ok: AST.HOSTSYNC``) — for one-off sites where a baseline entry
would outlive the code it describes.
"""

from __future__ import annotations

import ast
import os
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from maskclustering_tpu.analysis.findings import Finding, make_id

# modules where an unsanctioned host sync is a perf bug, not plumbing
DEVICE_PATH_MODULES = (
    "maskclustering_tpu/models/pipeline.py",
    "maskclustering_tpu/models/backprojection.py",
    "maskclustering_tpu/models/graph.py",
    "maskclustering_tpu/models/clustering.py",
    "maskclustering_tpu/models/postprocess_device.py",
    "maskclustering_tpu/models/streaming.py",
    "maskclustering_tpu/parallel/sharded.py",
    "maskclustering_tpu/parallel/batch.py",
    # io/feed.py is deliberately absent: the codec's encode half works on
    # host numpy by contract (it IS the declared h2d seam), and its device
    # decode half is covered by the Family-3 transfer guard
)

# functions dispatched onto worker threads from another module (the scene
# executors run run_scene_host on the host-tail DaemonFuture via a local
# closure; name-level thread-target collection cannot see through that)
THREAD_ENTRY_HINTS = ("run_scene_host",)

# jax entry points whose function-valued arguments get traced; the lax
# control-flow names are common words (pool.map, ex.map), so they only
# count when the call chain actually goes through lax
_TRACE_WRAPPERS = {"jit", "vmap", "pmap", "checkpoint", "remat",
                   "named_call", "custom_vjp", "custom_jvp"}
_LAX_TRACE_WRAPPERS = {"scan", "map", "while_loop", "cond", "switch",
                       "fori_loop", "associative_scan"}


def _is_trace_wrapper(chain: str) -> bool:
    tail = chain.rsplit(".", 1)[-1]
    if tail in _TRACE_WRAPPERS:
        return True
    return tail in _LAX_TRACE_WRAPPERS and "lax" in chain.split(".")

_MUTATOR_METHODS = {"append", "extend", "add", "update", "pop", "popitem",
                    "setdefault", "clear", "insert", "remove", "discard",
                    "appendleft", "extendleft"}

_WALLCLOCK_TIME_ATTRS = {"time", "perf_counter", "monotonic", "time_ns",
                         "perf_counter_ns", "monotonic_ns"}

_MUTABLE_CTORS = {"dict", "list", "set", "defaultdict", "deque",
                  "OrderedDict", "Counter"}


def _attr_chain(node: ast.AST) -> Optional[str]:
    """'np.random.default_rng' for nested Attribute/Name; None otherwise."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _line_optout(source_lines: Sequence[str], node: ast.AST,
                 check: str) -> bool:
    ln = getattr(node, "lineno", 0)
    if not (1 <= ln <= len(source_lines)):
        return False
    line = source_lines[ln - 1]
    return f"# mct-ok: {check}" in line or "# mct-ok: all" in line


class _Scope:
    """Qualname + per-(scope, token) ordinal bookkeeping for stable ids."""

    def __init__(self):
        self.stack: List[str] = []
        self.ordinals: Dict[Tuple[str, str], int] = {}

    @property
    def qualname(self) -> str:
        return ".".join(self.stack) or "<module>"

    def ordinal(self, token: str) -> int:
        key = (self.qualname, token)
        self.ordinals[key] = self.ordinals.get(key, 0) + 1
        return self.ordinals[key]


# ---------------------------------------------------------------------------
# AST.HOSTSYNC
# ---------------------------------------------------------------------------


def _with_is_sanctioned(node: ast.With) -> bool:
    """Is this ``with`` a declared pull seam? (see module docstring)

    Only the WITH ITEMS sanction — a ``sanctioned_pull`` context or a
    pull-declaring span. A body-level marker (a ``host_pull`` attr set, a
    booked ``d2h``) must NOT sanction its whole block: a 30-line span
    body with one booked pull would blind the lint to a second pull
    added anywhere in it — the exact regression this check exists to
    catch. Booked-but-unwrapped pulls are baseline entries instead.
    """
    for item in node.items:
        call = item.context_expr
        if not isinstance(call, ast.Call):
            continue
        chain = _attr_chain(call.func) or ""
        if chain.endswith("sanctioned_pull"):
            return True
        if chain.endswith(".span") or chain == "span":
            if any(kw.arg == "host_pull" for kw in call.keywords):
                return True
            if (call.args and isinstance(call.args[0], ast.Constant)
                    and isinstance(call.args[0].value, str)
                    and "pull" in call.args[0].value):
                return True
    return False


def check_host_syncs(tree: ast.Module, rel: str,
                     source_lines: Sequence[str]) -> List[Finding]:
    findings: List[Finding] = []
    scope = _Scope()

    def sync_token(call: ast.Call) -> Optional[str]:
        chain = _attr_chain(call.func)
        if chain in ("np.asarray", "numpy.asarray", "jax.device_get"):
            return chain
        if isinstance(call.func, ast.Attribute) \
                and call.func.attr in ("item", "block_until_ready"):
            return f".{call.func.attr}"
        if chain == "jax.block_until_ready":
            return chain
        if isinstance(call.func, ast.Name) and call.func.id in ("float", "bool") \
                and call.args and isinstance(call.args[0], ast.Call):
            return f"{call.func.id}(<call>)"
        return None

    def visit(node: ast.AST, sanctioned: bool) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            scope.stack.append(node.name)
            for child in ast.iter_child_nodes(node):
                visit(child, sanctioned)
            scope.stack.pop()
            return
        if isinstance(node, ast.With):
            sanctioned = sanctioned or _with_is_sanctioned(node)
        if isinstance(node, ast.Call):
            token = sync_token(node)
            if token and not sanctioned \
                    and not _line_optout(source_lines, node, "AST.HOSTSYNC"):
                findings.append(Finding(
                    id=make_id("AST.HOSTSYNC", rel, scope.qualname, token,
                               scope.ordinal(token)),
                    check="AST.HOSTSYNC", family="ast",
                    message=f"{token} outside a sanctioned host_pull seam "
                            f"(in {scope.qualname}) — an undeclared device "
                            f"sync on the device path",
                    file=rel, line=node.lineno))
        for child in ast.iter_child_nodes(node):
            visit(child, sanctioned)

    visit(tree, False)
    return findings


# ---------------------------------------------------------------------------
# AST.JITPURITY
# ---------------------------------------------------------------------------


def _collect_functions(tree: ast.Module) -> Dict[str, ast.AST]:
    """name -> def node for every (possibly nested) function in the module.

    Bare names: the module-local call graph resolves simple ``f(...)``
    calls; shadowing across scopes is rare enough that last-def-wins is an
    acceptable approximation for a linter.
    """
    out: Dict[str, ast.AST] = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            out[node.name] = node
    return out


def _function_args_of_call(call: ast.Call) -> Iterable[str]:
    for arg in list(call.args) + [kw.value for kw in call.keywords]:
        if isinstance(arg, ast.Name):
            yield arg.id


def _traced_roots(tree: ast.Module, funcs: Dict[str, ast.AST]) -> Set[str]:
    """Functions handed to jax tracing machinery (or decorated with it)."""
    roots: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            chain = _attr_chain(node.func) or ""
            if _is_trace_wrapper(chain):
                roots.update(n for n in _function_args_of_call(node)
                             if n in funcs)
            # functools.partial(jax.jit, ...)(impl)
            if isinstance(node.func, ast.Call):
                inner = node.func
                inner_chain = _attr_chain(inner.func) or ""
                if inner_chain.rsplit(".", 1)[-1] == "partial" and any(
                        _is_trace_wrapper(_attr_chain(a) or "")
                        for a in inner.args):
                    roots.update(n for n in _function_args_of_call(node)
                                 if n in funcs)
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                chain = _attr_chain(dec) or ""
                if isinstance(dec, ast.Call):
                    chain = _attr_chain(dec.func) or ""
                    if chain.rsplit(".", 1)[-1] == "partial" and any(
                            _is_trace_wrapper(_attr_chain(a) or "")
                            for a in dec.args):
                        roots.add(node.name)
                        continue
                if _is_trace_wrapper(chain):
                    roots.add(node.name)
    return roots


def _call_graph(funcs: Dict[str, ast.AST]) -> Dict[str, Set[str]]:
    graph: Dict[str, Set[str]] = {}
    for name, node in funcs.items():
        callees: Set[str] = set()
        for sub in ast.walk(node):
            if isinstance(sub, ast.Call) and isinstance(sub.func, ast.Name) \
                    and sub.func.id in funcs and sub.func.id != name:
                callees.add(sub.func.id)
        graph[name] = callees
    return graph


def _reachable(roots: Set[str], graph: Dict[str, Set[str]]) -> Set[str]:
    seen, work = set(roots), list(roots)
    while work:
        for callee in graph.get(work.pop(), ()):
            if callee not in seen:
                seen.add(callee)
                work.append(callee)
    return seen


def _impure_token(call: ast.Call) -> Optional[str]:
    chain = _attr_chain(call.func) or ""
    parts = chain.split(".")
    if len(parts) == 2 and parts[0] == "time" \
            and parts[1] in _WALLCLOCK_TIME_ATTRS:
        return chain
    if len(parts) >= 2 and parts[0] in ("np", "numpy") \
            and parts[1] == "random":
        return chain
    if len(parts) == 2 and parts[0] == "random":
        return chain
    if chain in ("datetime.now", "datetime.datetime.now", "os.urandom"):
        return chain
    return None


def check_jit_purity(tree: ast.Module, rel: str,
                     source_lines: Sequence[str]) -> List[Finding]:
    funcs = _collect_functions(tree)
    roots = _traced_roots(tree, funcs)
    if not roots:
        return []
    reachable = _reachable(roots, _call_graph(funcs))
    findings: List[Finding] = []
    ordinals: Dict[Tuple[str, str], int] = {}

    def walk_own_body(root: ast.AST) -> Iterable[ast.AST]:
        """ast.walk minus nested def bodies — a nested function is its own
        ``funcs`` entry, reached (or not) through the call graph; walking
        it here would double-report its calls and flag never-traced
        nested callbacks."""
        work = list(ast.iter_child_nodes(root))
        while work:
            node = work.pop()
            yield node
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                work.extend(ast.iter_child_nodes(node))

    for fname in sorted(reachable):
        for sub in walk_own_body(funcs[fname]):
            if isinstance(sub, ast.Call):
                token = _impure_token(sub)
                if token and not _line_optout(source_lines, sub,
                                              "AST.JITPURITY"):
                    key = (fname, token)
                    ordinals[key] = ordinals.get(key, 0) + 1
                    findings.append(Finding(
                        id=make_id("AST.JITPURITY", rel, fname, token,
                                   ordinals[key]),
                        check="AST.JITPURITY", family="ast",
                        message=f"{token} inside {fname}, which is "
                                f"reachable from jitted code — the value "
                                f"is baked at trace time, not read per "
                                f"call",
                        file=rel, line=sub.lineno))
    return findings


# ---------------------------------------------------------------------------
# AST.THREADS
# ---------------------------------------------------------------------------


def collect_thread_targets(tree: ast.Module) -> Set[str]:
    """Function names handed to DaemonFuture(...) / Thread(target=...)."""
    targets: Set[str] = set()
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        chain = _attr_chain(node.func) or ""
        tail = chain.rsplit(".", 1)[-1]
        if tail == "DaemonFuture" and node.args \
                and isinstance(node.args[0], ast.Name):
            targets.add(node.args[0].id)
        if tail == "Thread":
            for kw in node.keywords:
                if kw.arg == "target" and isinstance(kw.value, ast.Name):
                    targets.add(kw.value.id)
        # executor-shaped receivers: `ex`/`executor` AND `pool` spellings
        # (semantics/features.py's io pool is literally `pool.map(...)`)
        receiver = chain.lower()
        if tail in ("submit", "map") \
                and ("ex" in receiver or "pool" in receiver) and node.args \
                and isinstance(node.args[0], ast.Name):
            targets.add(node.args[0].id)
    return targets


def _module_level_mutables(tree: ast.Module) -> Set[str]:
    names: Set[str] = set()
    for stmt in tree.body:
        targets = []
        if isinstance(stmt, ast.Assign):
            targets, value = stmt.targets, stmt.value
        elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
            targets, value = [stmt.target], stmt.value
        else:
            continue
        mutable = isinstance(value, (ast.Dict, ast.List, ast.Set,
                                     ast.DictComp, ast.ListComp,
                                     ast.SetComp))
        if isinstance(value, ast.Call):
            chain = _attr_chain(value.func) or ""
            mutable = chain.rsplit(".", 1)[-1] in _MUTABLE_CTORS
        if mutable:
            names.update(t.id for t in targets if isinstance(t, ast.Name))
    return names


def _is_lock_guard(node: ast.With) -> bool:
    for item in node.items:
        expr = item.context_expr
        chain = _attr_chain(expr) or ""
        if isinstance(expr, ast.Call):
            chain = _attr_chain(expr.func) or chain
        if "lock" in chain.lower():
            return True
    return False


def check_thread_shared_state(tree: ast.Module, rel: str,
                              source_lines: Sequence[str],
                              thread_targets: Set[str]) -> List[Finding]:
    """Unlocked mutation of module-level mutable state in thread-reachable
    functions. ``thread_targets`` is the TREE-WIDE set of thread entry
    names (collect_thread_targets over every file + THREAD_ENTRY_HINTS);
    reachability closes within this module."""
    mutables = _module_level_mutables(tree)
    if not mutables:
        return []
    funcs = _collect_functions(tree)
    entries = {n for n in thread_targets if n in funcs}
    if not entries:
        return []
    reachable = _reachable(entries, _call_graph(funcs))
    findings: List[Finding] = []
    ordinals: Dict[Tuple[str, str], int] = {}

    def mutated_name(node: ast.AST) -> Optional[str]:
        if isinstance(node, (ast.Assign, ast.AugAssign)):
            targets = node.targets if isinstance(node, ast.Assign) else [node.target]
            for t in targets:
                base = t
                while isinstance(base, (ast.Subscript, ast.Attribute)):
                    base = base.value
                if isinstance(base, ast.Name) and base.id in mutables \
                        and base is not t:  # plain rebinding is not mutation
                    return base.id
        if isinstance(node, ast.Expr) and isinstance(node.value, ast.Call):
            call = node.value
            if isinstance(call.func, ast.Attribute) \
                    and call.func.attr in _MUTATOR_METHODS \
                    and isinstance(call.func.value, ast.Name) \
                    and call.func.value.id in mutables:
                return call.func.value.id
        return None

    def visit(node: ast.AST, fname: str, locked: bool) -> None:
        if isinstance(node, ast.With):
            locked = locked or _is_lock_guard(node)
        name = mutated_name(node)
        if name is not None and not locked \
                and not _line_optout(source_lines, node, "AST.THREADS"):
            key = (fname, name)
            ordinals[key] = ordinals.get(key, 0) + 1
            findings.append(Finding(
                id=make_id("AST.THREADS", rel, fname, name, ordinals[key]),
                check="AST.THREADS", family="ast",
                message=f"module-level {name!r} mutated in {fname} without "
                        f"a lock, and {fname} runs on an executor thread — "
                        f"the PR-3 registry-race pattern",
                file=rel, line=node.lineno))
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue  # nested defs are their own reachability entries
            visit(child, fname, locked)

    for fname in sorted(reachable):
        for child in ast.iter_child_nodes(funcs[fname]):
            visit(child, fname, False)
    return findings


# ---------------------------------------------------------------------------
# AST.EXCEPT
# ---------------------------------------------------------------------------


def check_bare_except(tree: ast.Module, rel: str,
                      source_lines: Sequence[str]) -> List[Finding]:
    findings: List[Finding] = []
    n = 0
    for node in ast.walk(tree):
        if isinstance(node, ast.ExceptHandler) and node.type is None \
                and not _line_optout(source_lines, node, "AST.EXCEPT"):
            n += 1
            findings.append(Finding(
                id=make_id("AST.EXCEPT", rel, n),
                check="AST.EXCEPT", family="ast",
                message="bare 'except:' swallows the typed fault classes "
                        "(utils/faults.py DeviceStallError carries "
                        "retry/degradation routing) — catch Exception or "
                        "narrower",
                file=rel, line=node.lineno))
    return findings


# ---------------------------------------------------------------------------
# the driver
# ---------------------------------------------------------------------------

# bench.py rides along for the thread/except lints (its supervisor owns a
# drain thread and the SIGTERM handler the concurrency family audits)
SCAN_ROOTS = ("maskclustering_tpu", "scripts", "bench.py")


def _iter_py_files(repo_root: str,
                   roots: Sequence[str] = SCAN_ROOTS) -> Iterable[str]:
    for root in roots:
        base = os.path.join(repo_root, root)
        if os.path.isfile(base) and base.endswith(".py"):
            yield base
            continue
        for dirpath, dirnames, filenames in os.walk(base):
            dirnames[:] = [d for d in dirnames if d != "__pycache__"]
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    yield os.path.join(dirpath, fn)


def analyze_ast(repo_root: str,
                roots: Sequence[str] = SCAN_ROOTS) -> List[Finding]:
    """Run Family 2 over the tree; pure stdlib, no jax import."""
    parsed: List[Tuple[str, ast.Module, List[str]]] = []
    thread_targets: Set[str] = set(THREAD_ENTRY_HINTS)
    for path in _iter_py_files(repo_root, roots):
        rel = os.path.relpath(path, repo_root).replace(os.sep, "/")
        try:
            with open(path, "r", encoding="utf-8") as f:
                source = f.read()
            tree = ast.parse(source, filename=path)
        except (OSError, SyntaxError) as e:
            parsed.append((rel, None, [f"{e}"]))
            continue
        lines = source.splitlines()
        parsed.append((rel, tree, lines))
        thread_targets |= collect_thread_targets(tree)

    findings: List[Finding] = []
    for rel, tree, lines in parsed:
        if tree is None:
            findings.append(Finding(
                id=make_id("AST.PARSE", rel), check="AST.PARSE", family="ast",
                message=f"could not parse: {lines[0]}", file=rel))
            continue
        if rel in DEVICE_PATH_MODULES:
            findings += check_host_syncs(tree, rel, lines)
        findings += check_jit_purity(tree, rel, lines)
        findings += check_thread_shared_state(tree, rel, lines,
                                              thread_targets)
        findings += check_bare_except(tree, rel, lines)
    return findings
