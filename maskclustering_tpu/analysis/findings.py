"""Finding records + the baseline/ratchet policy of mct-check.

A finding's ``id`` is STABLE: it is built from the check name plus
content-derived coordinates (file path, enclosing scope, offending token,
per-scope ordinal — never a raw line number), so an unrelated edit above
a finding does not churn the baseline. ``file:line`` is carried separately
for display only.

The baseline (``analysis_baseline.json``) is the ratchet: every entry
suppresses exactly one finding id and MUST carry a one-line justification
— an accepted trade, not a silenced alarm. A baseline entry whose finding
no longer fires is reported as stale (advisory), so the file only ever
shrinks or is consciously grown.
"""

from __future__ import annotations

import dataclasses
import json
import os
import re
from typing import Dict, List, Optional, Sequence, Set, Tuple

BASELINE_VERSION = 1
DEFAULT_BASELINE = "analysis_baseline.json"


@dataclasses.dataclass(frozen=True)
class Finding:
    """One invariant violation, stable-id'd and renderable."""

    id: str  # stable: <CHECK>:<content coordinates>, no line numbers
    check: str  # e.g. "IR.DTYPE.CLASS", "AST.HOSTSYNC"
    family: str  # "ir" | "ast"
    message: str  # one line, human-oriented
    file: str = ""  # repo-relative path ("" for whole-program IR findings)
    line: int = 0  # 1-based display anchor (0 = not line-anchored)

    @property
    def location(self) -> str:
        if not self.file:
            return "<ir>"
        return f"{self.file}:{self.line}" if self.line else self.file

    def to_json(self) -> Dict:
        return dataclasses.asdict(self)


def make_id(check: str, *coords: object) -> str:
    """Stable finding id: check name + content coordinates, ':'-joined."""
    return ":".join([check] + [str(c) for c in coords])


def load_baseline(path: Optional[str]) -> Dict[str, str]:
    """id -> justification from a baseline file; {} when absent.

    Raises ValueError on a malformed file or an entry missing its
    justification — a silent bad baseline would un-gate CI.
    """
    if not path or not os.path.exists(path):
        return {}
    with open(path, "r", encoding="utf-8") as f:
        doc = json.load(f)
    if not isinstance(doc, dict) or doc.get("version") != BASELINE_VERSION:
        raise ValueError(
            f"{path}: expected a baseline doc with version={BASELINE_VERSION}")
    out: Dict[str, str] = {}
    for entry in doc.get("suppressions", []):
        fid = entry.get("id")
        why = (entry.get("justification") or "").strip()
        if not fid or not why or why.startswith("TODO"):
            raise ValueError(
                f"{path}: every suppression needs an id AND a one-line "
                f"justification — write_baseline's TODO placeholders must "
                f"be replaced by a human (offending entry: {entry})")
        out[fid] = why
    return out


def write_baseline(path: str, findings: Sequence[Finding],
                   justifications: Optional[Dict[str, str]] = None) -> None:
    """Write a baseline covering ``findings``; keeps known justifications.

    New entries get a ``TODO`` justification that load_baseline REJECTS —
    a freshly written baseline cannot quietly become the gate; a human
    must replace every TODO with the actual accepted trade first.
    """
    justifications = justifications or {}
    doc = {
        "version": BASELINE_VERSION,
        "suppressions": [
            {"id": f.id,
             "justification": justifications.get(
                 f.id, "TODO: justify or fix"),
             "location": f.location,
             "message": f.message}
            for f in sorted(findings, key=lambda f: f.id)
        ],
    }
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(doc, fh, indent=2)
        fh.write("\n")


def partition_findings(
    findings: Sequence[Finding], baseline: Dict[str, str],
) -> Tuple[List[Finding], List[Finding], List[str]]:
    """(unsuppressed, suppressed, stale baseline ids).

    Unsuppressed findings gate (exit 2); suppressed ones render dimmed;
    stale ids are baseline entries whose finding no longer fires — the
    ratchet's "now delete the suppression" signal.
    """
    live = {f.id for f in findings}
    unsuppressed = [f for f in findings if f.id not in baseline]
    suppressed = [f for f in findings if f.id in baseline]
    stale = sorted(fid for fid in baseline if fid not in live)
    return unsuppressed, suppressed, stale


_FUSED_LABEL_RE = re.compile(r"fused@\d+x\d+")


def stale_in_scope(stale: Sequence[str], families: Sequence[str],
                   ir_labels: Optional[Set[str]] = None) -> List[str]:
    """Restrict stale baseline ids to the scope this run actually covered.

    A family-filtered run (``--families ast``) never re-derives the other
    family's findings — reporting those suppressions as stale would tell
    the user to delete still-valid entries, breaking the next full run.
    Same for ``fused@SxF``-labeled IR entries whose mesh this run did not
    lower (``ir_labels`` is the set of analyzed fused labels; ``None``
    means "don't filter by mesh" — the ir family did not run at all, so
    family scoping already handles it).
    """
    out: List[str] = []
    for fid in stale:
        family = ("ir" if fid.startswith("IR.")
                  else "ast" if fid.startswith("AST.")
                  else "concurrency" if fid.startswith("CONC.")
                  else "retrace" if fid.startswith("RETRACE.") else None)
        if family is not None and family not in families:
            continue
        if ir_labels is not None and family == "ir":
            m = _FUSED_LABEL_RE.search(fid)
            if m and m.group(0) not in ir_labels:
                continue
        out.append(fid)
    return out
