"""Family 5: the compile-surface analyzer (retrace, static half).

ROADMAP items 1 (scene-serving daemon) and 3 (persistent AOT executable
cache) assume a *closed compile surface*: every scene routes through a
bounded vocabulary of (stage fn, shape bucket, count_dtype, donation)
executables and a warm process never retraces. One Python scalar leaking
into a traced closure, or one jit wrapper rebuilt per call, silently
multiplies compiles — the measured cost is the 48 s/scene eager-retrace
regression ``_associate_scene_jit``'s docstring records, and the 106.6 s
warm-up BENCH_r03 measured is what a retrace re-buys per scene. Four
checks, all source-level (pure stdlib AST) except the census:

- **RETRACE.CAPTURE** — a traced function (jit root) closing over, or a
  ``jax.jit(functools.partial(...))`` binding, a name outside the
  compile-stable vocabulary (``COMPILE_STABLE_CAPTURES``: cfg, mesh,
  bucket params — the names builders are cached by). A per-scene value
  baked into a traced closure either recompiles per scene or silently
  serves scene A's constant to scene B.
- **RETRACE.BRANCH** — Python ``if``/``while``/ternary branching on
  ``.shape``/``.ndim``/``.size``/``len()`` inside traced code. A
  trace-time shape branch forks the executable per shape OUTSIDE the
  bucket vocabulary: within one bucket it is dead weight, across buckets
  it is compile surface the bucket key cannot see. Shape *reads* are
  fine (shapes are static); *branching* needs a ``# mct-ok:
  RETRACE.BRANCH`` audit mark tying it to a bucketed input.
- **RETRACE.STATIC** — jit-site hygiene: ``static_argnums``/
  ``static_argnames`` must be literal constants (an expression-valued
  vocabulary is unauditable), and a ``jax.jit`` call inside a plain
  function builds a FRESH executable cache per call — it must live at
  module scope, under ``functools.lru_cache``, or in a builder whose
  callers cache (``CACHED_BY_CALLER``).
- **RETRACE.SURFACE** — the census + ratchet: every jit site in the
  device-path modules must be classified (``SERVING_PROGRAMS`` — the
  per-scene executables, each with its bucket/dtype/donation key axes —
  or ``AUX_PROGRAMS`` with a reason), the census of executables a
  canonical mixed-bucket workload requires is computed through the REAL
  bucket classifier (``utils/compile_cache.scene_bucket``) plus the
  fused-step lowerings (the obs/cost.py AOT seam), and the result must
  equal the committed ``compile_surface_baseline.json`` exactly — growth
  or shrinkage fails with the offending (fn, bucket, dtype, donation)
  coordinate. Degradation-ladder rungs that legitimately add surface
  (donation-off, host-postprocess) are enumerated per rung, which is the
  vocabulary the runtime sanitizer's context tags check against.

- **RETRACE.GOLDENS** — the mct-sentinel ratchet: the committed
  ``canary_goldens.json`` (obs/canary.py) must cover EXACTLY the digest
  coordinates the canonical workload produces under the census cfg —
  growth and shrinkage both fail, and version skew demands an audited
  ``--write-goldens`` regeneration, same discipline as the surface
  baseline.

The dynamic half (``retrace_sanitizer``) hooks actual compile events and
asserts the serve-many contract at run time; fn names here and compile
log names there are ONE vocabulary.
"""

from __future__ import annotations

import ast
import builtins
import hashlib
import json
import os
import re
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from maskclustering_tpu.analysis.ast_checks import (
    _attr_chain,
    _line_optout,
)
from maskclustering_tpu.analysis.findings import Finding, make_id

# ---------------------------------------------------------------------------
# policy constants (the contracts, in one place)
# ---------------------------------------------------------------------------

DEFAULT_SURFACE_BASELINE = "compile_surface_baseline.json"
SURFACE_VERSION = 1

# the device-path modules whose jit sites ARE the compile surface
RETRACE_SCAN_ROOTS = (
    "maskclustering_tpu/models",
    "maskclustering_tpu/parallel",
    "maskclustering_tpu/ops",
    "maskclustering_tpu/io/feed.py",
    # the sentinel digest programs ride every scene/chunk host phase —
    # they are serving surface like any post-process kernel
    "maskclustering_tpu/obs/digest.py",
)

# names a traced closure / jit-partial may bind: the compile-stable
# builder parameters (builders are cached per these — lru_cache keys,
# shape-bucket coordinates, config-derived statics). Anything else baked
# into a traced program is per-scene state and RETRACE.CAPTURE fires.
COMPILE_STABLE_CAPTURES = frozenset({
    "cfg", "mesh", "k_max", "r_pad", "k2", "s_pad", "count_dtype", "donate",
    "window", "distance_threshold", "depth_trunc", "few_points_threshold",
    "coverage_threshold", "frame_batch", "max_len", "scale",
})

# builders that create a jit wrapper per call BY DESIGN, because their
# callers cache (parallel/batch._cached_step is lru_cached; the cost
# observatory lowers offline) — a new builder needs a caching story
# before it joins this set
CACHED_BY_CALLER = frozenset({"build_fused_step", "build_stage_step"})

# ---------------------------------------------------------------------------
# the program registry: every jit site classified
# ---------------------------------------------------------------------------

# the per-scene serving surface (single-chip path), name -> (key, flags):
#   key: "scene"  = one executable per (k_max, f_pad, n_pad) scene bucket
#        "masks"  = keyed by the data-dependent m_pad bucket (recorded as
#                   the "masks" shape-bucket kind; pow2-bounded)
#        "post"   = keyed by the device post-process's data-dependent pow2
#                   buckets (recorded as the post.* shape-bucket kinds)
#        "stream" = the streaming accumulator's programs (models/
#                   streaming.py), keyed by the stream's (m_pad, f_alloc,
#                   n_pad) bucket (the "stream" shape-bucket kind) — ONE
#                   bucket per stream by construction (every chunk pads
#                   to the same coordinates), so the bucket's FIRST chunk
#                   compiles them and every later chunk (and later
#                   same-bucket stream) dispatches warm. On a FROZEN
#                   serving daemon a cold stream bucket books post-freeze
#                   compiles exactly like a cold scene bucket: warm it or
#                   expect the gate to say so
#        "config" = one executable per config (static scalars only)
#   flags: subset of {"dtype", "donate"} — extra key axes
SERVING_PROGRAMS: Tuple[Tuple[str, str, Tuple[str, ...]], ...] = (
    ("_decode_depth_jit", "scene", ()),
    ("_vox_size_jit", "config", ()),
    ("_associate_scene_impl", "scene", ("dtype", "donate")),
    ("compute_graph_stats", "masks", ("dtype",)),
    ("observer_schedule_device", "scene", ()),
    ("_iterative_clustering_jit", "masks", ("dtype",)),
    ("_iterative_clustering_warm_jit", "stream", ("dtype",)),
    ("_stream_merge_impl", "stream", ("dtype",)),
    ("_stream_recluster_impl", "stream", ("dtype",)),
    ("_rep_plane_update_impl", "stream", ()),
    ("_live_count_kernel", "post", ()),
    ("_prep_kernel", "post", ()),
    ("_node_stats_kernel", "post", ("dtype",)),
    ("_dbscan_split_kernel", "post", ()),
    ("_group_structs_kernel", "post", ()),
    ("_survivor_gather_kernel", "post", ("dtype",)),
    ("_mask_group_counts_impl", "post", ("dtype", "donate")),
    # mct-sentinel invariant digests (obs/digest.py): fixed int32/uint32
    # internally, so NO dtype/donate key axes — one executable per scene
    # bucket x m_pad (keyed like the masks-bucket programs) and one per
    # stream bucket; both compile during prewarm because they ride every
    # warm-up scene's host phase
    ("_digest_scene_impl", "masks", ()),
    ("_digest_stream_impl", "stream", ()),
)

# jit sites that are NOT per-scene serving executables, with the reason
# they stay off the census (a new jit site must land in one table or the
# other — RETRACE.SURFACE flags the unclassified)
AUX_PROGRAMS: Dict[str, str] = {
    "estimate_spacing": "traced inside _vox_size_jit / the fused step; "
                        "standalone dispatch is test-only",
    "associate_frame": "traced inside the association scan; standalone "
                       "dispatch is test-only",
    "ball_query": "exact-parity path (use_exact_ball_query), not the "
                  "bucketed serving path",
    "ball_query_pallas": "TPU Pallas kernel, probe-gated benchmark path",
    "grid_dbscan_pairs": "embedded in _dbscan_split_kernel's program; the "
                         "standalone jit is the diagnostics dispatch",
    # the fused mesh path: its executable is the census's "fused" section
    # (one per mesh, lowered through the obs/cost.py seam)
    "per_scene": "the fused mesh step (census 'fused' section; cached by "
                 "parallel/batch._cached_step)",
    "batched": "jit(vmap(per_scene)) wrapper of the fused mesh step",
    # build_stage_step's per-stage programs: AOT cost observatory only
    "fn": "build_stage_step stage program — AOT-lowered by the cost "
          "observatory, never dispatched in serving",
    "post": "build_stage_step postprocess stage program — observatory only",
}

# surface the degradation ladder legitimately ADDS per rung (fn names the
# runtime sanitizer allows to compile anew under that context tag; rungs
# absent here add nothing). donation-off rebuilds exactly the donating
# programs; host-postprocess routes to the numpy path and compiles nothing
RUNG_SURFACE: Dict[str, Tuple[str, ...]] = {
    "sequential-executor": (),
    "single-chip": (),
    "donation-off": ("_associate_scene_impl", "_mask_group_counts_impl"),
    "host-postprocess": (),
}

# the canonical mixed-bucket workload the census enumerates: two distinct
# scene buckets plus a repeat (the serve-many case the sanitizer pins).
# Coordinates go through the REAL classifier (compile_cache.scene_bucket),
# so a bucketing-math change shows up as a census diff
CANONICAL_WORKLOAD: Tuple[Dict, ...] = (
    {"scene": "A", "frames": 10, "points": 16000, "max_id": 14},
    {"scene": "B", "frames": 34, "points": 60000, "max_id": 100},
    {"scene": "A-repeat", "frames": 10, "points": 16000, "max_id": 14},
)


# ---------------------------------------------------------------------------
# jit-site collection (shared by the capture/static/surface checks)
# ---------------------------------------------------------------------------


def _is_jit_chain(chain: Optional[str]) -> bool:
    if not chain:
        return False
    tail = chain.rsplit(".", 1)[-1]
    return tail in ("jit", "pjit")


def _is_partial_chain(chain: Optional[str]) -> bool:
    return bool(chain) and chain.rsplit(".", 1)[-1] == "partial"


class JitSite:
    """One jax.jit/pjit occurrence: where, what it traces, its statics."""

    __slots__ = ("rel", "line", "def_line", "root_names", "root_nodes",
                 "static_kw", "partial_bound_names", "enclosing",
                 "decorated")

    def __init__(self, rel: str, line: int, def_line: int = 0):
        self.rel = rel
        self.line = line
        self.def_line = def_line or line
        self.root_names: List[str] = []  # traced fn names (vocabulary)
        self.root_nodes: List[ast.AST] = []  # def/lambda nodes when local
        self.static_kw: List[ast.keyword] = []
        self.partial_bound_names: List[str] = []  # Names bound via partial
        self.enclosing: Optional[str] = None  # enclosing FunctionDef name
        self.decorated = False


_IGNORED_ROOTS = frozenset({"jax", "jnp", "np", "functools", "partial",
                            "lax"})


def _collect_defs(tree: ast.Module) -> Dict[str, ast.AST]:
    """name -> def/lambda/partial-call node for root resolution.

    ``x = functools.partial(f, k=v)`` binds x to the partial Call node, so
    a later ``jax.jit(x)`` resolves through it to ``f`` (and the bound
    keyword names are checked like closure captures).
    """
    out: Dict[str, ast.AST] = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            out[node.name] = node
        elif isinstance(node, ast.Assign):
            value = node.value
            is_partial = (isinstance(value, ast.Call)
                          and _is_partial_chain(_attr_chain(value.func)))
            if isinstance(value, ast.Lambda) or is_partial:
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        out[t.id] = value
    return out


def _resolve_roots(site: JitSite, call_args: Sequence[ast.AST],
                   defs: Dict[str, ast.AST]) -> None:
    """Traced-root names of a jit(...) call: Names inside the function
    argument expression (handles jit(vmap(f)), jit(partial(f, ...)), and
    names bound to lambdas or partials)."""
    names: List[str] = []
    for arg in call_args:
        if isinstance(arg, ast.Lambda):
            # a literal lambda argument IS the traced root
            site.root_names.append("<lambda>")
            site.root_nodes.append(arg)
            continue
        for sub in ast.walk(arg):
            if isinstance(sub, ast.Name) and sub.id not in _IGNORED_ROOTS:
                names.append(sub.id)
    resolved = [n for n in names if n in defs]
    for n in resolved or names[:1]:
        node = defs.get(n)
        if isinstance(node, ast.Call):
            # a partial binding: the real root is the wrapped function;
            # the bound keyword Names are traced-in values to capture-check
            _resolve_roots(site, node.args, defs)
            for kw in node.keywords:
                for sub in ast.walk(kw.value):
                    if isinstance(sub, ast.Name):
                        site.partial_bound_names.append(sub.id)
            continue
        if n not in site.root_names:
            site.root_names.append(n)
            if node is not None:
                site.root_nodes.append(node)


def collect_jit_sites(tree: ast.Module, rel: str) -> List[JitSite]:
    """Every jit occurrence in a module: decorators, direct calls, and
    ``partial(jax.jit, ...)`` applications, with enclosing-scope info."""
    defs = _collect_defs(tree)
    sites: List[JitSite] = []
    stack: List[str] = []

    def visit(node: ast.AST) -> None:
        is_fn = isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
        decorators = ()
        if is_fn:
            decorators = tuple(node.decorator_list)
            for dec in decorators:
                site = _site_from_decorator(dec, node, rel)
                if site is not None:
                    site.enclosing = stack[-1] if stack else None
                    sites.append(site)
            stack.append(node.name)
        if isinstance(node, ast.Call):
            site = _site_from_call(node, defs, rel)
            if site is not None:
                site.enclosing = stack[-1] if stack else None
                sites.append(site)
        for child in ast.iter_child_nodes(node):
            if child in decorators:
                # already classified above — recursing into a call-form
                # decorator (`@jax.jit(donate_argnums=...)`) would mint a
                # phantom second site inside the function's own scope
                continue
            visit(child)
        if is_fn:
            stack.pop()

    visit(tree)
    return sites


def _site_from_decorator(dec: ast.AST, fn_node: ast.AST,
                         rel: str) -> Optional[JitSite]:
    chain = _attr_chain(dec)
    statics: List[ast.keyword] = []
    if isinstance(dec, ast.Call):
        chain = _attr_chain(dec.func)
        if _is_partial_chain(chain) and any(
                _is_jit_chain(_attr_chain(a)) for a in dec.args):
            statics = [kw for kw in dec.keywords
                       if kw.arg in ("static_argnums", "static_argnames")]
        elif not _is_jit_chain(chain):
            return None
        else:
            statics = [kw for kw in dec.keywords
                       if kw.arg in ("static_argnums", "static_argnames")]
    elif not _is_jit_chain(chain):
        return None
    # the site anchors at the DECORATOR line (where the jit lives, and
    # where an inline `# mct-ok:` marker goes); def_line keeps the def
    # as a second marker anchor
    site = JitSite(rel, dec.lineno, def_line=fn_node.lineno)
    site.root_names.append(fn_node.name)
    site.root_nodes.append(fn_node)
    site.static_kw = statics
    site.decorated = True
    return site


def _site_from_call(node: ast.Call, defs: Dict[str, ast.AST],
                    rel: str) -> Optional[JitSite]:
    chain = _attr_chain(node.func)
    if _is_jit_chain(chain):
        site = JitSite(rel, node.lineno)
        site.static_kw = [kw for kw in node.keywords
                          if kw.arg in ("static_argnums", "static_argnames")]
        _resolve_roots(site, node.args, defs)
        # jit(functools.partial(f, k=v)): the bound Names are part of the
        # traced program exactly like closure captures
        for arg in node.args:
            if isinstance(arg, ast.Call) \
                    and _is_partial_chain(_attr_chain(arg.func)):
                for kw in arg.keywords:
                    for sub in ast.walk(kw.value):
                        if isinstance(sub, ast.Name):
                            site.partial_bound_names.append(sub.id)
        return site
    # functools.partial(jax.jit, ...)(f) applications
    if isinstance(node.func, ast.Call):
        inner = node.func
        if _is_partial_chain(_attr_chain(inner.func)) and any(
                _is_jit_chain(_attr_chain(a)) for a in inner.args):
            site = JitSite(rel, node.lineno)
            site.static_kw = [kw for kw in inner.keywords
                              if kw.arg in ("static_argnums",
                                            "static_argnames")]
            _resolve_roots(site, node.args, defs)
            return site
    return None


# ---------------------------------------------------------------------------
# RETRACE.CAPTURE
# ---------------------------------------------------------------------------

_BUILTIN_NAMES = frozenset(dir(builtins))


def _bound_names(fn: ast.AST) -> Set[str]:
    """Names bound in a function's own scope (args + stores + imports +
    nested def names), excluding nested function bodies."""
    out: Set[str] = set()
    args = fn.args
    for a in (args.posonlyargs + args.args + args.kwonlyargs):
        out.add(a.arg)
    if args.vararg:
        out.add(args.vararg.arg)
    if args.kwarg:
        out.add(args.kwarg.arg)
    work = list(ast.iter_child_nodes(fn)) if not isinstance(fn, ast.Lambda) \
        else [fn.body]
    while work:
        node = work.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            out.add(node.name)
            continue  # its body is its own scope
        if isinstance(node, ast.ClassDef):
            out.add(node.name)
            continue
        if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Store):
            out.add(node.id)
        if isinstance(node, (ast.Import, ast.ImportFrom)):
            for alias in node.names:
                out.add((alias.asname or alias.name).split(".")[0])
        if isinstance(node, ast.ExceptHandler) and node.name:
            out.add(node.name)
        if isinstance(node, (ast.comprehension,)):
            for sub in ast.walk(node.target):
                if isinstance(sub, ast.Name):
                    out.add(sub.id)
        work.extend(ast.iter_child_nodes(node))
    return out


def _free_names(fn: ast.AST) -> Set[str]:
    """Free variables of a function node: reads not bound locally, plus
    the free variables of nested defs minus this scope's bindings."""
    bound = _bound_names(fn)
    reads: Set[str] = set()
    nested: List[ast.AST] = []
    work = list(ast.iter_child_nodes(fn)) if not isinstance(fn, ast.Lambda) \
        else [fn.body]
    while work:
        node = work.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            nested.append(node)
            continue
        if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load):
            reads.add(node.id)
        work.extend(ast.iter_child_nodes(node))
    free = reads - bound
    for sub in nested:
        free |= _free_names(sub) - bound
    return free


def _module_names(tree: ast.Module) -> Set[str]:
    """Module-scope bindings: top-level defs/classes/assigns + ALL imports
    (an import inside a builder binds a module object — compile-stable)."""
    out: Set[str] = set()
    for stmt in tree.body:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            out.add(stmt.name)
        elif isinstance(stmt, ast.Assign):
            for t in stmt.targets:
                for sub in ast.walk(t):
                    if isinstance(sub, ast.Name):
                        out.add(sub.id)
        elif isinstance(stmt, ast.AnnAssign) and isinstance(stmt.target,
                                                            ast.Name):
            out.add(stmt.target.id)
    for node in ast.walk(tree):
        if isinstance(node, (ast.Import, ast.ImportFrom)):
            for alias in node.names:
                out.add((alias.asname or alias.name).split(".")[0])
    return out


def check_captures(tree: ast.Module, rel: str,
                   source_lines: Sequence[str]) -> List[Finding]:
    """Traced closures / jit-partials binding non-compile-stable names."""
    module_names = _module_names(tree)
    # a captured name that is itself a function (a sibling nested helper
    # inside the same cached builder, a lambda binding) is a compile-stable
    # callable traced into the program, not per-scene state
    fn_names = set(_collect_defs(tree))
    findings: List[Finding] = []
    for site in collect_jit_sites(tree, rel):
        captured: Set[str] = set()
        for node in site.root_nodes:
            if site.enclosing is None and not isinstance(node, ast.Lambda):
                continue  # a module-level def cannot close over locals
            captured |= (_free_names(node) - module_names - _BUILTIN_NAMES)
        captured |= {n for n in site.partial_bound_names
                     if n not in module_names and n not in _BUILTIN_NAMES}
        bad = sorted(captured - COMPILE_STABLE_CAPTURES - fn_names)
        for name in bad:
            anchor = site.root_nodes[0] if site.root_nodes else None
            if anchor is not None and _line_optout(source_lines, anchor,
                                                   "RETRACE.CAPTURE"):
                continue
            scope = site.enclosing or "<module>"
            root = site.root_names[0] if site.root_names else "<anon>"
            findings.append(Finding(
                id=make_id("RETRACE.CAPTURE", rel, scope, root, name),
                check="RETRACE.CAPTURE", family="retrace",
                message=f"traced function {root!r} (in {scope}) bakes "
                        f"{name!r} into its program — not in the "
                        f"compile-stable capture vocabulary, so it either "
                        f"retraces per call or serves a stale constant",
                file=rel, line=site.line))
    return findings


# ---------------------------------------------------------------------------
# RETRACE.BRANCH
# ---------------------------------------------------------------------------

_SHAPE_ATTRS = ("shape", "ndim", "size")


def _shape_token_in(test: ast.AST) -> Optional[str]:
    for sub in ast.walk(test):
        if isinstance(sub, ast.Attribute) and sub.attr in _SHAPE_ATTRS:
            base = _attr_chain(sub.value)
            return f"{base or '<expr>'}.{sub.attr}"
        if isinstance(sub, ast.Call) and isinstance(sub.func, ast.Name) \
                and sub.func.id == "len":
            return "len()"
    return None


def check_shape_branches(tree: ast.Module, rel: str,
                         source_lines: Sequence[str]) -> List[Finding]:
    """Trace-time shape/len branching inside traced code (jit roots plus
    module-local functions they call)."""
    from maskclustering_tpu.analysis.ast_checks import (
        _call_graph,
        _collect_functions,
        _reachable,
    )

    funcs = _collect_functions(tree)
    roots: Set[str] = set()
    for site in collect_jit_sites(tree, rel):
        roots.update(n for n in site.root_names if n in funcs)
    if not roots:
        return []
    reachable = _reachable(roots, _call_graph(funcs))
    findings: List[Finding] = []
    ordinals: Dict[str, int] = {}

    def walk_own_body(root: ast.AST):
        """ast.walk minus nested def bodies — a nested function is its own
        ``funcs`` entry, reached through the call graph; walking it here
        would report its branches twice under two finding ids."""
        work = list(ast.iter_child_nodes(root))
        while work:
            node = work.pop()
            yield node
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                work.extend(ast.iter_child_nodes(node))

    for fname in sorted(reachable):
        for node in walk_own_body(funcs[fname]):
            if isinstance(node, (ast.If, ast.While)):
                token = _shape_token_in(node.test)
            elif isinstance(node, ast.IfExp):
                token = _shape_token_in(node.test)
            else:
                continue
            if token is None or _line_optout(source_lines, node,
                                             "RETRACE.BRANCH"):
                continue
            ordinals[fname] = ordinals.get(fname, 0) + 1
            findings.append(Finding(
                id=make_id("RETRACE.BRANCH", rel, fname, ordinals[fname]),
                check="RETRACE.BRANCH", family="retrace",
                message=f"trace-time branch on {token} inside {fname} "
                        f"(reachable from a jit root) — forks the "
                        f"executable per shape outside the bucket "
                        f"vocabulary; audit it against a bucketed input "
                        f"and mark '# mct-ok: RETRACE.BRANCH'",
                file=rel, line=node.lineno))
    return findings


# ---------------------------------------------------------------------------
# RETRACE.STATIC
# ---------------------------------------------------------------------------


def _is_literal_static(value: ast.AST) -> bool:
    if isinstance(value, ast.Constant):
        return True
    if isinstance(value, (ast.Tuple, ast.List)):
        return all(isinstance(e, ast.Constant) for e in value.elts)
    if isinstance(value, ast.IfExp):
        return _is_literal_static(value.body) and _is_literal_static(
            value.orelse)
    return False


_CACHE_DECOS = ("lru_cache", "cache")


def _has_cache_decorator(fn: ast.AST) -> bool:
    for dec in getattr(fn, "decorator_list", ()):
        chain = _attr_chain(dec) or ""
        if isinstance(dec, ast.Call):
            chain = _attr_chain(dec.func) or chain
        if chain.rsplit(".", 1)[-1] in _CACHE_DECOS:
            return True
    return False


def check_static_hygiene(tree: ast.Module, rel: str,
                         source_lines: Sequence[str]) -> List[Finding]:
    findings: List[Finding] = []
    funcs = {n.name: n for n in ast.walk(tree)
             if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))}
    seen_lines: Set[int] = set()
    for site in collect_jit_sites(tree, rel):
        root = site.root_names[0] if site.root_names else "<anon>"
        for kw in site.static_kw:
            if not _is_literal_static(kw.value):
                findings.append(Finding(
                    id=make_id("RETRACE.STATIC", rel, root, kw.arg,
                               "nonliteral"),
                    check="RETRACE.STATIC", family="retrace",
                    message=f"{kw.arg} at the {root!r} jit site is a "
                            f"computed expression — the static-argument "
                            f"vocabulary must be literal so the compile "
                            f"surface is auditable",
                    file=rel, line=site.line))
        if site.decorated or site.enclosing is None:
            continue
        enclosing = funcs.get(site.enclosing)
        if enclosing is None or _has_cache_decorator(enclosing) \
                or site.enclosing in CACHED_BY_CALLER:
            continue
        if site.line in seen_lines or _line_anchored_optout(
                source_lines, site.line, "RETRACE.STATIC"):
            continue
        seen_lines.add(site.line)
        findings.append(Finding(
            id=make_id("RETRACE.STATIC", rel, site.enclosing, root, "fresh"),
            check="RETRACE.STATIC", family="retrace",
            message=f"jax.jit inside {site.enclosing} builds a fresh "
                    f"executable cache on every call (traced root "
                    f"{root!r}) — hoist to module scope, lru_cache the "
                    f"builder, or register it in CACHED_BY_CALLER with a "
                    f"caching story",
            file=rel, line=site.line))
    return findings


def _line_anchored_optout(source_lines: Sequence[str], line: int,
                          check: str) -> bool:
    if not (1 <= line <= len(source_lines)):
        return False
    text = source_lines[line - 1]
    return f"# mct-ok: {check}" in text or "# mct-ok: all" in text


# ---------------------------------------------------------------------------
# RETRACE.SURFACE: the compile-surface census + ratchet
# ---------------------------------------------------------------------------


def classify_jit_sites(parsed: Sequence[Tuple[str, ast.Module,
                                              Sequence[str]]]
                       ) -> Tuple[Set[str], List[Finding]]:
    """(all traced-root names, unclassified-site findings).

    Every jit site's traced root must be a SERVING_PROGRAMS entry or an
    AUX_PROGRAMS entry — the source-level half of the surface ratchet: a
    brand-new jit site cannot join the tree without being placed on (or
    explicitly off) the census.
    """
    serving = {name for name, _, _ in SERVING_PROGRAMS}
    known = serving | set(AUX_PROGRAMS)
    roots: Set[str] = set()
    findings: List[Finding] = []
    for rel, tree, lines in parsed:
        if tree is None:
            continue
        for site in collect_jit_sites(tree, rel):
            for name in site.root_names or ["<anon>"]:
                label = name if name != "<lambda>" else \
                    f"<lambda>@{site.enclosing or rel}"
                roots.add(label)
                sanctioned = (_line_anchored_optout(lines, site.line,
                                                    "RETRACE.SURFACE")
                              or _line_anchored_optout(
                                  lines, site.def_line, "RETRACE.SURFACE"))
                if label not in known and not sanctioned:
                    findings.append(Finding(
                        id=make_id("RETRACE.SURFACE", rel, "unclassified",
                                   label),
                        check="RETRACE.SURFACE", family="retrace",
                        message=f"jit site traces {label!r}, which is in "
                                f"neither SERVING_PROGRAMS nor "
                                f"AUX_PROGRAMS — a new executable joined "
                                f"the compile surface unclassified "
                                f"(analysis/retrace.py registry)",
                        file=rel, line=site.line))
    return roots, findings


def check_registry_stale(roots: Set[str]) -> List[Finding]:
    """Registry entries no jit site traces anymore (real-repo runs only —
    a seeded fixture tree legitimately contains almost no programs)."""
    serving = {name for name, _, _ in SERVING_PROGRAMS}
    findings: List[Finding] = []
    for name in sorted((serving | set(AUX_PROGRAMS)) - roots):
        findings.append(Finding(
            id=make_id("RETRACE.SURFACE", "registry", "stale", name),
            check="RETRACE.SURFACE", family="retrace",
            message=f"program registry names {name!r} but no jit site in "
                    f"the scanned tree traces it — the registry (or the "
                    f"baseline census) is stale",
            file="maskclustering_tpu/analysis/retrace.py"))
    return findings


def compile_surface(cfg=None) -> Dict:
    """The census: executables the canonical workload requires, as a
    JSON-able doc. Bucket coordinates go through the REAL classifier
    (``utils/compile_cache.scene_bucket``)."""
    from maskclustering_tpu.utils.compile_cache import scene_bucket

    if cfg is None:
        from maskclustering_tpu.obs.cost import default_pipeline_cfg

        cfg = default_pipeline_cfg(point_chunk=8192).replace(
            frame_pad_multiple=32, mask_pad_multiple=256)
    buckets: List[Tuple[int, int, int]] = []
    for scene in CANONICAL_WORKLOAD:
        b = scene_bucket(cfg, scene["frames"], scene["points"],
                         scene["max_id"])
        if b not in buckets:
            buckets.append(b)
    rows: List[str] = []
    donate = "on" if cfg.donate_buffers else "off"
    for name, key, flags in SERVING_PROGRAMS:
        coords: List[str]
        if key == "scene":
            coords = [f"bucket=k{k}:f{f}:n{n}" for k, f, n in buckets]
        elif key in ("masks", "post", "stream"):
            coords = [f"bucket=<data:{key}>"]
        else:
            coords = ["bucket=<config>"]
        for coord in coords:
            row = f"fn={name} {coord}"
            if "dtype" in flags:
                row += f" dtype={cfg.count_dtype}"
            if "donate" in flags:
                row += f" donate={donate}"
            rows.append(row)
    return {
        "version": SURFACE_VERSION,
        "workload": [dict(s) for s in CANONICAL_WORKLOAD],
        "config": {"count_dtype": cfg.count_dtype,
                   "donate_buffers": bool(cfg.donate_buffers),
                   "frame_pad_multiple": cfg.frame_pad_multiple,
                   "point_chunk": cfg.point_chunk,
                   "mask_pad_multiple": cfg.mask_pad_multiple},
        "surface": sorted(rows),
        "rungs": {k: sorted(v) for k, v in RUNG_SURFACE.items()},
    }


# anchor the close on ") ->" (the result arrow): a bare first-")" stop
# truncates at sharding annotations that themselves contain parens — the
# 3-axis meshes' device-order transposes lower as e.g. "<=[2,4]T(1,0)"
_MAIN_SIG_RE = re.compile(r"func\.func public @main\((.*?)\)\s*->",
                          re.DOTALL)
_TENSOR_RE = re.compile(r"tensor<([^>]+)>")


def fused_surface_rows(lowerings: Dict[Tuple[int, ...],
                                       Tuple[str, str]]) -> List[str]:
    """One census row per fused-step lowering: mesh + the argument-shape
    digest read from the ACTUAL StableHLO main signature (the obs/cost.py
    AOT seam) — a silent signature change is a surface change. Mesh keys
    are (scene, frame) or (scene, frame, point) tuples; the label is the
    shared SxF / SxFxP vocabulary (parallel.mesh.mesh_label), so the
    point-sharded fused-step variants are first-class census rows."""
    from maskclustering_tpu.analysis.ir_checks import _mesh_label

    rows: List[str] = []
    for mesh, (stablehlo, _) in sorted(lowerings.items()):
        m = _MAIN_SIG_RE.search(stablehlo)
        shapes = _TENSOR_RE.findall(m.group(1)) if m else []
        digest = hashlib.sha1(
            ";".join(shapes).encode("utf-8")).hexdigest()[:12]
        rows.append(f"fn=per_scene mesh={_mesh_label(mesh)} "
                    f"args={len(shapes)} sig={digest}")
    return rows


def load_surface_baseline(path: str) -> Optional[Dict]:
    if not os.path.exists(path):
        return None
    with open(path, "r", encoding="utf-8") as f:
        doc = json.load(f)
    if not isinstance(doc, dict) or doc.get("version") != SURFACE_VERSION:
        raise ValueError(f"{path}: expected a compile-surface baseline "
                         f"with version={SURFACE_VERSION}")
    return doc


def write_surface_baseline(path: str, census: Dict,
                           fused_rows: Optional[List[str]] = None) -> None:
    doc = dict(census)
    if fused_rows is not None:
        doc["fused"] = sorted(fused_rows)
    with open(path, "w", encoding="utf-8") as f:
        json.dump(doc, f, indent=2)
        f.write("\n")


def check_surface(census: Dict, baseline: Dict,
                  fused_rows: Optional[List[str]] = None) -> List[Finding]:
    """The ratchet: census == baseline exactly, growth AND shrinkage."""
    findings: List[Finding] = []

    def diff(kind: str, current: Iterable[str], committed: Iterable[str]):
        cur, com = set(current), set(committed)
        for row in sorted(cur - com):
            findings.append(Finding(
                id=make_id("RETRACE.SURFACE", kind, "grew", row),
                check="RETRACE.SURFACE", family="retrace",
                message=f"compile surface grew: {row} is required by the "
                        f"canonical workload but absent from the baseline "
                        f"— a new compile variant appeared; audit it, "
                        f"then regenerate with --write-surface"))
        for row in sorted(com - cur):
            findings.append(Finding(
                id=make_id("RETRACE.SURFACE", kind, "shrank", row),
                check="RETRACE.SURFACE", family="retrace",
                message=f"compile surface shrank: baseline row '{row}' is "
                        f"no longer produced — the baseline is stale; "
                        f"regenerate with --write-surface"))

    diff("serving", census["surface"], baseline.get("surface", []))
    for rung in sorted(set(census["rungs"]) | set(baseline.get("rungs", {}))):
        diff(f"rung:{rung}", census["rungs"].get(rung, []),
             (baseline.get("rungs") or {}).get(rung, []))
    if fused_rows is not None and "fused" in baseline:
        # a --mesh-filtered run only lowers a lattice subset: compare the
        # committed rows for the meshes actually analyzed (same scoping as
        # findings.stale_in_scope), so a filtered run never reports the
        # other meshes' rows as shrinkage
        analyzed = {m.group(1) for r in fused_rows
                    if (m := re.search(r"mesh=(\S+)", r))}
        committed = [r for r in baseline["fused"]
                     if (m := re.search(r"mesh=(\S+)", r))
                     and m.group(1) in analyzed]
        diff("fused", fused_rows, committed)
    return findings


def expected_goldens_coords(cfg=None) -> Set[str]:
    """The coordinate set canary_goldens.json MUST cover: one full-scene
    digest coordinate per DISTINCT canonical-workload bucket, under the
    census cfg (``obs/canary.goldens_config`` — the same knobs
    ``compile_surface`` pins). Derived, never read from the file."""
    from maskclustering_tpu.utils.compile_cache import scene_bucket

    if cfg is None:
        from maskclustering_tpu.obs.canary import goldens_config

        cfg = goldens_config()
    coords: Set[str] = set()
    for scene in CANONICAL_WORKLOAD:
        k, f, n = scene_bucket(cfg, scene["frames"], scene["points"],
                               scene["max_id"])
        coords.add(f"k{k}:f{f}:n{n}|{cfg.count_dtype}|single|r0|c0")
    return coords


def check_goldens(repo_root: str,
                  goldens_path: Optional[str] = None) -> List[Finding]:
    """The sentinel-goldens ratchet: the committed canary goldens must
    cover EXACTLY the canonical workload's digest coordinates.

    Growth and shrinkage both fail loudly — an uncovered coordinate means
    the canary plane silently stopped guarding a bucket; a stale
    coordinate means the file describes executables the workload no
    longer produces (false "uncovered" probes at serve time). Version
    skew and unreadability are their own findings, same as the
    compile-surface baseline.
    """
    from maskclustering_tpu.obs import digest as digest_mod
    from maskclustering_tpu.obs.canary import (DEFAULT_GOLDENS_PATH,
                                               GOLDENS_VERSION)

    path = goldens_path or os.path.join(repo_root, DEFAULT_GOLDENS_PATH)
    findings: List[Finding] = []
    if not os.path.exists(path):
        findings.append(Finding(
            id=make_id("RETRACE.GOLDENS", "missing"),
            check="RETRACE.GOLDENS", family="retrace",
            message=f"no {DEFAULT_GOLDENS_PATH} at the repo root — the "
                    f"canary sentinel is un-gated; generate one with "
                    f"scripts/load_gen.py --write-goldens and commit it"))
        return findings
    try:
        with open(path, "r", encoding="utf-8") as f:
            doc = json.load(f)
        if not isinstance(doc, dict) or not isinstance(
                doc.get("goldens"), dict):
            raise ValueError("not a goldens doc (missing 'goldens' map)")
    except (OSError, ValueError) as e:
        findings.append(Finding(
            id=make_id("RETRACE.GOLDENS", "unreadable"),
            check="RETRACE.GOLDENS", family="retrace",
            message=f"canary goldens unreadable: {e}"))
        return findings
    if doc.get("version") != GOLDENS_VERSION \
            or doc.get("digest_version") != digest_mod.DIGEST_VERSION:
        findings.append(Finding(
            id=make_id("RETRACE.GOLDENS", "version"),
            check="RETRACE.GOLDENS", family="retrace",
            message=f"canary goldens carry version "
                    f"{doc.get('version')}/digest "
                    f"{doc.get('digest_version')} but the code wants "
                    f"{GOLDENS_VERSION}/{digest_mod.DIGEST_VERSION} — a "
                    f"schema change without regeneration; rerun "
                    f"--write-goldens and audit the diff"))
        return findings
    expected = expected_goldens_coords()
    committed = set(doc["goldens"])
    for coord in sorted(expected - committed):
        findings.append(Finding(
            id=make_id("RETRACE.GOLDENS", "uncovered", coord),
            check="RETRACE.GOLDENS", family="retrace",
            message=f"canary goldens shrank: canonical-workload "
                    f"coordinate {coord} has no committed golden — the "
                    f"sentinel cannot verify that bucket; regenerate "
                    f"with --write-goldens"))
    for coord in sorted(committed - expected):
        findings.append(Finding(
            id=make_id("RETRACE.GOLDENS", "stale", coord),
            check="RETRACE.GOLDENS", family="retrace",
            message=f"canary goldens grew: committed coordinate {coord} "
                    f"is not produced by the canonical workload under "
                    f"the census cfg — stale entry (knob or workload "
                    f"change); audit it, then regenerate with "
                    f"--write-goldens"))
    return findings


# ---------------------------------------------------------------------------
# the driver
# ---------------------------------------------------------------------------


def _iter_scan_files(repo_root: str) -> Iterable[str]:
    for root in RETRACE_SCAN_ROOTS:
        base = os.path.join(repo_root, root)
        if os.path.isfile(base):
            yield base
            continue
        for dirpath, dirnames, filenames in os.walk(base):
            dirnames[:] = [d for d in dirnames if d != "__pycache__"]
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    yield os.path.join(dirpath, fn)


def analyze_retrace(
    repo_root: str,
    *,
    lowerings: Optional[Dict[Tuple[int, int], Tuple[str, str]]] = None,
    lower_missing: bool = True,
    surface_baseline: Optional[str] = None,
) -> List[Finding]:
    """Run Family 5's static half end-to-end.

    ``lowerings`` maps a mesh to precomputed (stablehlo, compiled hlo)
    texts of the fused step at the canonical shape —
    ``obs.cost.observe_costs(..., keep_texts=True)`` produces them, and
    the tier-1 conftest's session-scoped ``fused_lattice_aot`` fixture
    shares ONE sweep between the cost tests, the IR gate and this census.
    Without them (and with ``lower_missing``) the census lowers the
    lattice itself (~15 s of CPU AOT). ``lower_missing=False`` skips the
    fused section entirely (pure-AST mode for fixture trees).
    """
    parsed: List[Tuple[str, ast.Module, Sequence[str]]] = []
    for path in _iter_scan_files(repo_root):
        rel = os.path.relpath(path, repo_root).replace(os.sep, "/")
        try:
            with open(path, "r", encoding="utf-8") as f:
                source = f.read()
            tree = ast.parse(source, filename=path)
        except (OSError, SyntaxError) as e:
            parsed.append((rel, None, [f"{e}"]))
            continue
        parsed.append((rel, tree, source.splitlines()))

    findings: List[Finding] = []
    for rel, tree, lines in parsed:
        if tree is None:
            findings.append(Finding(
                id=make_id("RETRACE.PARSE", rel), check="RETRACE.PARSE",
                family="retrace", message=f"could not parse: {lines[0]}",
                file=rel))
            continue
        findings += check_captures(tree, rel, lines)
        findings += check_shape_branches(tree, rel, lines)
        findings += check_static_hygiene(tree, rel, lines)

    roots, cls_findings = classify_jit_sites(
        [(r, t, ln) for r, t, ln in parsed if t is not None])
    findings += cls_findings

    # the census + registry-staleness halves only make sense against the
    # real repo — the marker below distinguishes it from seeded fixture
    # trees (which legitimately contain almost no programs)
    marker = os.path.join(repo_root, "maskclustering_tpu", "analysis",
                          "retrace.py")
    if not os.path.exists(marker):
        return findings
    findings += check_registry_stale(roots)
    # the sentinel-goldens ratchet rides the same real-repo gate (it runs
    # even when the surface baseline is missing — the two files ratchet
    # independently)
    findings += check_goldens(repo_root)
    baseline_path = surface_baseline or os.path.join(
        repo_root, DEFAULT_SURFACE_BASELINE)
    try:
        baseline = load_surface_baseline(baseline_path)
    except (ValueError, OSError) as e:
        findings.append(Finding(
            id=make_id("RETRACE.SURFACE", "baseline", "unreadable"),
            check="RETRACE.SURFACE", family="retrace",
            message=f"compile-surface baseline unreadable: {e}"))
        return findings
    if baseline is None:
        findings.append(Finding(
            id=make_id("RETRACE.SURFACE", "baseline", "missing"),
            check="RETRACE.SURFACE", family="retrace",
            message=f"no {DEFAULT_SURFACE_BASELINE} at the repo root — "
                    f"the surface ratchet is un-gated; generate one with "
                    f"--write-surface and commit it"))
        return findings
    census = compile_surface()
    fused_rows = None
    if lowerings is None and lower_missing:
        from maskclustering_tpu.analysis.ir_checks import (
            CANONICAL_SHAPE,
            FULL_LATTICE,
        )
        from maskclustering_tpu.obs.cost import ensure_cpu_devices, observe_costs

        ensure_cpu_devices(8)
        rows = observe_costs(FULL_LATTICE, stages=("fused",),
                             keep_texts=True, **CANONICAL_SHAPE)
        lowerings = {tuple(r["mesh"]): (r["stablehlo"], r["compiled_text"])
                     for r in rows if "stablehlo" in r}
    if lowerings:
        fused_rows = fused_surface_rows(lowerings)
    findings += check_surface(census, baseline, fused_rows)
    return findings
