"""mct-check: static invariant analysis over the pipeline's contracts.

PRs 3-5 established hard perf/robustness contracts — 2 host syncs per
scene, buffer donation actually consumed, every counting contraction on
the narrow MXU path, 2-byte collectives under pure scene-DP, lock-guarded
shared state across the three executor threads — but they lived as prose
in ARCHITECTURE.md plus a handful of point tests, and each one was won
back from a real regression (the PR-3 review caught an unlocked metrics
registry; the PR-4 census found f32 dots that had silently survived).
This package verifies them from the lowered IR and the source AST on
every CI run, so the scene-serving daemon and device-resident-tail
rewrites cannot silently undo them. Five families:

- **Family 1 — IR invariants** (``ir_checks``): AOT-lowers the fused
  step over CPU virtual devices (the obs/cost.py seam; nothing is ever
  materialized) and checks the StableHLO/HLO text: counting-dtype policy
  conformance, the 2-sync host-transfer census, donation aliasing, and
  the scene-DP/frame-sharded collective payload budgets across the
  divisor lattice of 8.
- **Family 2 — AST lint** (``ast_checks``): walks ``maskclustering_tpu/``
  + ``scripts/`` for unsanctioned host-sync calls, wall-clock/randomness
  reachable from jitted code, unlocked module-level state mutated on
  executor threads (the PR-3 registry race as the motivating pattern),
  and bare ``except:`` that would swallow the typed fault classes of
  ``utils/faults.py``.
- **Family 3 — runtime sanitizer** (``transfer_guard``): opt-in
  ``jax.transfer_guard("disallow")`` around ``run_scene_device``
  (``--transfer-guard`` / ``MCT_TRANSFER_GUARD``) so implicit transfers
  the AST lint cannot see become hard errors on CPU in CI.
- **Family 4 — concurrency** (``concurrency`` + ``lock_sanitizer``,
  ``--families concurrency``): a whole-program thread-topology model
  (roots = DaemonFuture / Thread / executor submits / signal handlers /
  watchdog targets / ``# mct-thread: root`` markers) checked for
  unguarded multi-root shared state, lock-order cycles, blocking calls
  under held locks, handler purity, and join/abandon contracts — plus
  the opt-in instrumented lock shim (``MCT_LOCK_SANITIZER=1``) whose
  observed acquisition-order graph must embed in the static one.
- **Family 5 — retrace** (``retrace`` + ``retrace_sanitizer``,
  ``--families retrace``): the compile-surface gate behind the
  compile-once/serve-many contract. Static half: traced-closure capture
  lint (RETRACE.CAPTURE), trace-time shape branching (RETRACE.BRANCH),
  jit-site hygiene (RETRACE.STATIC), and a compile-surface census —
  every jit site classified, executables enumerated through the REAL
  bucket classifier plus the fused-step AOT lowerings, ratcheted against
  ``compile_surface_baseline.json`` (RETRACE.SURFACE). Dynamic half: the
  opt-in compile-event sanitizer (``MCT_RETRACE_SANITIZER=1``) hooks
  jax's compile log per (fn, signature, ladder rung) and asserts a warm
  same-bucket scene books zero new compiles.

Findings carry stable ids + ``file:line``; a committed
``analysis_baseline.json`` suppresses accepted pre-existing findings
(each with a one-line justification) so the gate starts green and only
ratchets. CLI::

    python -m maskclustering_tpu.analysis [--baseline analysis_baseline.json] \
        [--format text|json] [--events out.jsonl]

exits 0 clean, 2 on unsuppressed findings.
"""

from maskclustering_tpu.analysis.findings import (  # noqa: F401
    Finding,
    load_baseline,
    partition_findings,
)
