"""Concurrency family, dynamic half: the opt-in instrumented lock shim.

The static analyzer (``analysis/concurrency.py``) proves the lock-order
graph it can SEE is acyclic; it cannot see orders taken through
first-class callables, C extensions, or config-dependent paths. This shim
records what actually happens: every acquisition of a named pipeline lock
is logged against the acquiring thread's currently-held named locks,
producing the OBSERVED order graph, plus hold-time accounting that
surfaces locks held across blocking work (a hold longer than
``MCT_LOCK_HOLD_WARN_S`` is recorded as a long hold). The cross-check —
every observed edge must embed in the static graph
(``check_embeds``; tests/test_faults.py runs the PR-5 canned 4-scene
fault plan under ``MCT_LOCK_SANITIZER=1``) — closes the loop: each side
catches what the other can't.

Creation seam: the five named pipeline locks (``utils/faults.py``'s plan
/ heartbeat / fault-entry locks, ``obs/events.py``'s sink lock,
``obs/metrics.py``'s registry lock) are created through ``mct_lock(name)``.
Off (the default), ``mct_lock`` returns a RAW ``threading.Lock`` — zero
overhead on the metrics hot path (obs/metrics.py budgets ~100 ns per
counter bump; a Python-level wrapper would triple that). Armed
(``MCT_LOCK_SANITIZER=1`` before import, or ``arm(True)`` +
``instrument_known_locks()`` for the process-global locks that already
exist), acquire/release cost a few dict operations each — a drill/CI
knob, never a production default.

The lock NAMES here and the static analyzer's lock identities are ONE
vocabulary: ``mct_lock``'s literal argument is the node id in both
graphs, so the embed check compares like with like.

Stdlib-only at module scope (utils/faults.py imports this and must stay
importable without jax; obs counters are emitted lazily).
"""

from __future__ import annotations

import os
import threading
import time
from typing import Dict, List, Optional, Set, Tuple

ENV_FLAG = "MCT_LOCK_SANITIZER"

# a hold crossing this many seconds is recorded as a "long hold" — the
# dynamic analogue of the static blocking-call-under-lock check (a lock
# held across device work or file IO shows up here even when the blocking
# call was invisible to the AST)
DEFAULT_LONG_HOLD_S = 0.05

_armed: Optional[bool] = None  # None -> the environment decides


def arm(on: Optional[bool]) -> None:
    """Explicitly enable/disable the sanitizer (``None`` defers to env).

    Arming affects locks created AFTER this call; for the process-global
    locks created at import time, follow with ``instrument_known_locks``.
    """
    global _armed
    _armed = on


def enabled() -> bool:
    if _armed is not None:
        return _armed
    return os.environ.get(ENV_FLAG, "").strip().lower() in ("1", "true",
                                                            "on", "yes")


def long_hold_threshold_s() -> float:
    try:
        return float(os.environ.get("MCT_LOCK_HOLD_WARN_S",
                                    str(DEFAULT_LONG_HOLD_S)))
    except ValueError:
        return DEFAULT_LONG_HOLD_S


# ---------------------------------------------------------------------------
# observed state (process-global, guarded by a PLAIN lock — the sanitizer
# must never instrument itself)
# ---------------------------------------------------------------------------


class _State:
    """Acquisition orders + hold times observed since the last reset."""

    def __init__(self):
        self.lock = threading.Lock()  # plain on purpose
        # read once per reset(), not per release — an environ lookup +
        # float parse on every lock release would tax the armed hot path
        self.long_hold_s = long_hold_threshold_s()
        self.acquisitions: Dict[str, int] = {}
        self.edges: Dict[Tuple[str, str], int] = {}  # (held, acquired) -> n
        self.max_hold_s: Dict[str, float] = {}
        self.long_holds: List[Dict] = []  # {"name", "seconds", "thread"}
        self._tls = threading.local()  # per-thread held stack

    def _held(self) -> List[Tuple[str, float]]:
        stack = getattr(self._tls, "stack", None)
        if stack is None:
            stack = self._tls.stack = []
        return stack

    def on_acquire(self, name: str) -> None:
        held = self._held()
        with self.lock:
            self.acquisitions[name] = self.acquisitions.get(name, 0) + 1
            for outer, _ in held:
                if outer != name:
                    edge = (outer, name)
                    self.edges[edge] = self.edges.get(edge, 0) + 1
        held.append((name, time.monotonic()))

    def on_release(self, name: str) -> None:
        held = self._held()
        t0 = None
        for i in range(len(held) - 1, -1, -1):  # tolerate non-LIFO release
            if held[i][0] == name:
                t0 = held.pop(i)[1]
                break
        if t0 is None:
            return
        dt = time.monotonic() - t0
        with self.lock:
            if dt > self.max_hold_s.get(name, 0.0):
                self.max_hold_s[name] = dt
            if dt >= self.long_hold_s:
                self.long_holds.append({
                    "name": name, "seconds": round(dt, 4),
                    "thread": threading.current_thread().name})


_STATE = _State()


def reset() -> None:
    """Drop everything observed so far (test isolation)."""
    global _STATE
    _STATE = _State()


def observed_edges() -> Set[Tuple[str, str]]:
    """(held, then-acquired) name pairs seen since the last reset."""
    with _STATE.lock:
        return set(_STATE.edges)


def report() -> Dict:
    """JSON-able digest of everything observed since the last reset."""
    with _STATE.lock:
        return {
            "acquisitions": dict(_STATE.acquisitions),
            "order_edges": {f"{a} -> {b}": n
                            for (a, b), n in sorted(_STATE.edges.items())},
            "max_hold_s": {k: round(v, 4)
                           for k, v in sorted(_STATE.max_hold_s.items())},
            "long_holds": list(_STATE.long_holds),
        }


def emit_counters() -> None:
    """Book the digest on the obs metrics registry (lazy import): the run
    report's Faults section then renders the sanitizer line for free."""
    try:
        from maskclustering_tpu.obs import metrics
    except Exception:  # noqa: BLE001 — accounting never faults the shim
        return
    with _STATE.lock:
        acq = sum(_STATE.acquisitions.values())
        edges = len(_STATE.edges)
        holds = len(_STATE.long_holds)
    metrics.count("locks.acquisitions", float(acq))
    metrics.count("locks.order_edges", float(edges))
    metrics.count("locks.long_holds", float(holds))


# ---------------------------------------------------------------------------
# the lock shim
# ---------------------------------------------------------------------------


class InstrumentedLock:
    """``threading.Lock`` wrapper that records order + hold time by name."""

    __slots__ = ("name", "_lock")

    def __init__(self, name: str, lock: Optional[threading.Lock] = None):
        self.name = name
        self._lock = lock if lock is not None else threading.Lock()

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        ok = self._lock.acquire(blocking, timeout)
        if ok:
            _STATE.on_acquire(self.name)
        return ok

    def release(self) -> None:
        _STATE.on_release(self.name)
        self._lock.release()

    def locked(self) -> bool:
        return self._lock.locked()

    def __enter__(self) -> bool:
        return self.acquire()

    def __exit__(self, *exc) -> None:
        self.release()


def mct_lock(name: str):
    """The named-lock creation seam: raw ``threading.Lock`` when the
    sanitizer is off (zero overhead), ``InstrumentedLock`` when armed.

    ``name`` is the lock's identity in BOTH graphs: the static analyzer
    reads this literal out of the call site, the shim stamps it on every
    observation — the embed cross-check compares one vocabulary.
    """
    if enabled():
        return InstrumentedLock(name)
    return threading.Lock()


def instrument_known_locks():
    """Swap the import-time process-global locks for instrumented ones.

    ``mct_lock`` instruments at CREATION time; the plan lock and the
    metrics registry's lock already exist by the time a test (or
    ``run.py --lock-sanitizer``) arms the sanitizer mid-process, so they
    are re-wrapped in place here. Per-instance locks (EventSink, Heartbeat,
    fault entries) are created after arming and need no swap. Returns an
    undo callable that restores the original lock objects.

    Swapping while another thread HOLDS one of these locks would lose the
    release pairing — callers arm at a quiescent point (process start, a
    test fixture's setup) by contract.
    """
    from maskclustering_tpu.obs import metrics
    from maskclustering_tpu.utils import faults

    originals = [
        (faults, "_PLAN_LOCK", faults._PLAN_LOCK),
        (metrics.registry(), "_lock", metrics.registry()._lock),
    ]
    # wrap the LIVE lock objects (the `lock=` seam): a straggler thread
    # still holding or blocked on the original keeps synchronizing on the
    # same primitive as post-swap acquirers — exclusion survives the swap
    faults._PLAN_LOCK = InstrumentedLock(
        "faults._PLAN_LOCK", faults._PLAN_LOCK)
    metrics.registry()._lock = InstrumentedLock(
        "obs.metrics.Registry._lock", metrics.registry()._lock)

    def undo():
        for obj, attr, lock in originals:
            setattr(obj, attr, lock)

    return undo


# ---------------------------------------------------------------------------
# the cross-check
# ---------------------------------------------------------------------------


def check_embeds(observed: Set[Tuple[str, str]],
                 static_edges: Set[Tuple[str, str]],
                 static_nodes: Set[str]) -> List[str]:
    """Violations of "the observed order graph embeds in the static one".

    An observed edge between two statically-known locks that the static
    graph does not carry is exactly the case the sanitizer exists for: an
    acquisition order taken through a path the AST could not follow. Edges
    touching locks the static side never saw (ad-hoc test locks) are out
    of scope — the embed check compares the shared vocabulary only.
    """
    out: List[str] = []
    for a, b in sorted(observed):
        if a not in static_nodes or b not in static_nodes:
            continue
        if (a, b) not in static_edges:
            out.append(
                f"observed lock order {a} -> {b} is absent from the static "
                f"lock-order graph — an order path the AST cannot see; "
                f"model it (or refactor the nesting away)")
    return out
