"""Retrace family, dynamic half: the opt-in compile-event sanitizer.

The static analyzer (``analysis/retrace.py``) proves the compile surface
it can SEE is closed: every jit site classified, every traced closure
capturing only compile-stable names, the census of executables pinned to
``compile_surface_baseline.json``. It cannot see a retrace born at run
time — a jit wrapper rebuilt per call, an eager op chain dispatching tiny
programs per scene, a cfg field that silently became part of a traced
closure. This shim records what actually compiles: jax's per-executable
build log (``jax_log_compiles`` — "Compiling <fn> with global shapes and
types [...]") is intercepted by a logging filter, keyed
``(fn, signature-digest, ladder-context)``, and checked against the
serve-many contract:

- a **repeat key** (the same program compiled twice in one context) is a
  jit-cache thrash — the exact bug class ``_associate_scene_jit``'s
  docstring records as a measured 48 s/scene regression — and is always
  a violation;
- after ``freeze()`` (a warm process; tests call it once their workload's
  shape buckets have all been seen) any NEW key is a violation: a warm
  same-bucket scene books **zero** compiles, which is the economics the
  scene-serving daemon and the persistent AOT cache are built on;
- degradation-ladder rungs that legitimately add surface (donation-off,
  host-postprocess) switch the **context** tag (run.py's supervisor calls
  ``set_context`` when the ladder drops a rung), so their recompiles are
  new keys in a new context — surface the baseline enumerates, not
  repeat-violations.

The bucket classifier is ONE vocabulary across both halves:
``utils/compile_cache.record_shape_bucket`` notifies this shim of every
new shape bucket (``note_bucket``), so the digest can say "N compiles
against M new buckets" — a warm run reads 0/0.

Opt-in via ``run.py --retrace-sanitizer`` or ``MCT_RETRACE_SANITIZER=1``;
off (the default) nothing is hooked and ``jax_log_compiles`` stays
untouched. Results are identical either way — the hook only observes.

Stdlib-only at module scope (``utils/compile_cache`` imports this and
must stay importable without jax; jax is imported inside ``install``).
"""

from __future__ import annotations

import contextlib
import hashlib
import logging
import os
import re
import threading
from typing import Dict, List, Optional, Set, Tuple

ENV_FLAG = "MCT_RETRACE_SANITIZER"

# the jax loggers that carry the jax_log_compiles messages (0.4.x: the
# "Compiling ..." line is pxla's; the tracing/lowering timing lines are
# dispatch's; the persistent-cache hit/miss chatter is compiler's — all
# are intercepted so an armed run stays quiet)
_JAX_COMPILE_LOGGERS = ("jax._src.interpreters.pxla", "jax._src.dispatch",
                        "jax._src.compiler")

# "Compiling <fn> with global shapes and types [sig]. Argument mapping: ..."
# <fn> may contain spaces ("<unnamed wrapped function>") and [sig] spans
# lines for wide programs, hence the non-greedy DOTALL match
_COMPILING_RE = re.compile(
    r"^Compiling (?P<fn>.+?) with global shapes and types "
    r"(?P<sig>.*)\. Argument mapping", re.DOTALL)

# jax_log_compiles side-chatter suppressed (not recorded) while armed
_NOISE_PREFIXES = ("Finished tracing + transforming",
                   "Finished jaxpr to MLIR module conversion",
                   "Finished XLA compilation",
                   # persistent-compilation-cache chatter (jax flips these
                   # to visible levels under jax_log_compiles; the HIT
                   # signal itself arrives via jax.monitoring)
                   "Persistent compilation cache hit",
                   "Persistent compilation cache miss",
                   "PERSISTENT COMPILATION CACHE MISS")

DEFAULT_CONTEXT = "baseline"

_armed: Optional[bool] = None  # None -> the environment decides


def arm(on: Optional[bool]) -> None:
    """Explicitly enable/disable the sanitizer (``None`` defers to env).

    Arming is observed by ``note_bucket`` immediately; the compile hook
    itself needs ``install()`` (run.py does both).
    """
    global _armed
    _armed = on


def enabled() -> bool:
    if _armed is not None:
        return _armed
    return os.environ.get(ENV_FLAG, "").strip().lower() in ("1", "true",
                                                            "on", "yes")


# ---------------------------------------------------------------------------
# observed state (process-global, plain lock — compiles are rare events)
# ---------------------------------------------------------------------------


# thread-local restore marker: compiles recorded while an AOT-cache
# restore window is open are cache restores, not serving compiles
_RESTORE_TLS = threading.local()


class _State:
    """Compile events keyed (fn, signature digest, context) since reset."""

    def __init__(self):
        self.lock = threading.Lock()
        self.keys: Dict[Tuple[str, str, str], int] = {}
        self.first_sig: Dict[Tuple[str, str, str], str] = {}
        self.violations: List[Dict] = []
        self.context = DEFAULT_CONTEXT
        self.frozen = False
        self.buckets_new = 0
        self.backend_compiles = 0
        self.aot_restores = 0
        # persistent-compilation-cache correlation: jax logs "Compiling
        # <fn>" BEFORE the backend compile, then fires the
        # /jax/compilation_cache/cache_hits monitoring event synchronously
        # on the same thread when the "compile" was really a cache
        # deserialize — so the last key recorded per thread is the one a
        # hit event reclassifies (utils/aot_cache.py is built on this:
        # cache hits are not compiles)
        self.cache_hits: Dict[Tuple[str, str, str], int] = {}
        self.pending = threading.local()

    def on_compile(self, fn: str, sig: str) -> None:
        if getattr(_RESTORE_TLS, "active", False):
            # an AOT-cache restore compiling its deserialized module: a
            # warm start, not serving surface — counted separately, never
            # a key/violation
            with self.lock:
                self.aot_restores += 1
            return
        digest = hashlib.sha1(sig.encode("utf-8", "replace")).hexdigest()[:12]
        with self.lock:
            key = (fn, digest, self.context)
            n = self.keys.get(key, 0) + 1
            self.keys[key] = n
            self.pending.key = key
            if n == 1:
                self.first_sig[key] = sig[:200]
            if n > 1:
                # the same (fn, signature, context) built a second
                # executable: the jit cache that should have served it was
                # dropped or bypassed — always a violation
                self.violations.append({
                    "kind": "repeat", "fn": fn, "sig": digest,
                    "context": self.context, "count": n})
            elif self.frozen and not _rung_sanctioned(fn, self.context):
                self.violations.append({
                    "kind": "post_freeze", "fn": fn, "sig": digest,
                    "context": self.context})

    def on_cache_event(self, hit: bool) -> None:
        """One /jax/compilation_cache/cache_{hits,misses} event: resolve
        this thread's pending key (hit -> reclassified as a cache hit)."""
        with self.lock:
            key = getattr(self.pending, "key", None)
            self.pending.key = None
            if hit and key is not None:
                self.cache_hits[key] = self.cache_hits.get(key, 0) + 1
                # a hit means the "compile" was a persistent-cache
                # deserialize (a warm restart replaying a prior process's
                # executable — the WAL-recovery path depends on this), so
                # the post_freeze violation on_compile provisionally
                # recorded for this build is retracted; repeat violations
                # stay — a rebuilt key still means the in-process jit
                # cache was dropped, however the bytes were produced
                fn, digest, context = key
                for i in range(len(self.violations) - 1, -1, -1):
                    v = self.violations[i]
                    if (v["kind"] == "post_freeze" and v["fn"] == fn
                            and v["sig"] == digest
                            and v["context"] == context):
                        del self.violations[i]
                        break


def _rung_sanctioned(fn: str, context: str) -> bool:
    """Is a post-freeze compile of ``fn`` enumerated surface under this
    ladder context? A frozen long-lived process (the serving daemon this
    gate protects) legitimately degrades — the baseline's per-rung
    allowance (``analysis.retrace.RUNG_SURFACE``, the same vocabulary the
    static census commits) says exactly which programs may rebuild there;
    everything else stays a violation even in a new context."""
    if context == DEFAULT_CONTEXT:
        return False
    try:
        from maskclustering_tpu.analysis.retrace import RUNG_SURFACE
    except Exception:  # noqa: BLE001 — no table, no sanction
        return False
    allowed: set = set()
    for rung in context.split("+"):
        allowed.update(RUNG_SURFACE.get(rung, ()))
    return fn in allowed


_STATE = _State()


def reset() -> None:
    """Drop everything observed so far (test isolation)."""
    global _STATE
    _STATE = _State()


def set_context(tag: str) -> None:
    """Tag subsequent compiles with a degradation-ladder context.

    run.py's scene supervisor calls this when the ladder drops a rung
    (between executor rounds — the queue is drained, so no in-flight
    compile straddles the switch). Same-signature recompiles under a new
    tag are new keys, not repeat-violations: donation-off legitimately
    rebuilds its donating programs, and the surface baseline enumerates
    exactly which (``compile_surface_baseline.json`` "rungs").
    """
    with _STATE.lock:
        _STATE.context = tag or DEFAULT_CONTEXT


def freeze() -> None:
    """Declare the process warm: every NEW key from here is a violation —
    except a degradation rung's enumerated programs under their context
    tag (``_rung_sanctioned``): a frozen serving process that drops to
    donation-off may rebuild exactly the baselined variants."""
    with _STATE.lock:
        _STATE.frozen = True


def thaw() -> None:
    with _STATE.lock:
        _STATE.frozen = False


def note_bucket(new: bool) -> None:
    """Bucket-classifier seam (utils/compile_cache.record_shape_bucket):
    counts new shape buckets so the digest reads compiles-vs-buckets."""
    if not new or not enabled():
        return
    with _STATE.lock:
        _STATE.buckets_new += 1


@contextlib.contextmanager
def restore_window():
    """Mark this thread's compiles as AOT-cache restores for the duration.

    utils/aot_cache.py opens this around deserialize+compile of a
    serialized executable: the wrapper's compile event is a warm start
    being paid from disk, not serving surface — booked on
    ``aot_restores``, never a key and never a violation.
    """
    prev = getattr(_RESTORE_TLS, "active", False)
    _RESTORE_TLS.active = True
    try:
        yield
    finally:
        _RESTORE_TLS.active = prev


def snapshot_keys() -> Set[Tuple[str, str, str]]:
    """The (fn, sig digest, context) keys observed since the last reset."""
    with _STATE.lock:
        return set(_STATE.keys)


def violations() -> List[Dict]:
    with _STATE.lock:
        return list(_STATE.violations)


def digest() -> Dict:
    """JSON-able digest of everything observed since the last reset.

    ``compiles`` counts genuine builds only: compile events the
    persistent compilation cache served (``cache_hits``) and AOT-cache
    restores (``aot_restores``) are warm starts paid from disk, not
    compile surface — a second process against warm caches reads
    ``compiles: 0``. ``raw_compiles`` keeps the uncorrelated event count.
    """
    with _STATE.lock:
        by_fn: Dict[str, int] = {}
        for (fn, _, _), n in _STATE.keys.items():
            by_fn[fn] = by_fn.get(fn, 0) + n
        raw = sum(_STATE.keys.values())
        hits = sum(_STATE.cache_hits.values())
        return {
            "compiles": max(raw - hits, 0),
            "raw_compiles": raw,
            "cache_hits": hits,
            "aot_restores": _STATE.aot_restores,
            "distinct_keys": len(_STATE.keys),
            "by_fn": dict(sorted(by_fn.items())),
            "violations": list(_STATE.violations),
            "buckets_new": _STATE.buckets_new,
            "backend_compiles": _STATE.backend_compiles,
            "context": _STATE.context,
            "frozen": _STATE.frozen,
        }


def summary() -> Dict:
    """The compact serving-digest shape: ONE schema for the daemon's
    stats/digest line and the isolated worker's ready/bye lines — a field
    added here shows up identically in both topologies."""
    d = digest()
    return {
        "compiles": d["compiles"],
        "cache_hits": d["cache_hits"],
        "aot_restores": d["aot_restores"],
        "post_freeze": sum(1 for v in d["violations"]
                           if v["kind"] == "post_freeze"),
        "repeats": sum(1 for v in d["violations"] if v["kind"] == "repeat"),
        "frozen": d["frozen"],
    }


def emit_counters() -> None:
    """Book the digest on the obs metrics registry: the report's Analysis
    section renders the retrace line from these (obs/report.py)."""
    try:
        from maskclustering_tpu.obs import metrics
    except Exception:  # noqa: BLE001 — accounting never faults the shim
        return
    d = digest()
    metrics.count("retrace.compiles", float(d["compiles"]))
    metrics.count("retrace.distinct_programs", float(len(d["by_fn"])))
    metrics.count("retrace.buckets_new", float(d["buckets_new"]))
    if d["cache_hits"]:
        metrics.count("retrace.cache_hits", float(d["cache_hits"]))
    if d["aot_restores"]:
        metrics.count("retrace.aot_restores", float(d["aot_restores"]))
    repeats = sum(1 for v in d["violations"] if v["kind"] == "repeat")
    frozen = sum(1 for v in d["violations"] if v["kind"] == "post_freeze")
    if repeats:
        metrics.count("retrace.repeat_compiles", float(repeats))
    if frozen:
        metrics.count("retrace.post_freeze_compiles", float(frozen))


# ---------------------------------------------------------------------------
# the hook: a logging filter over jax's compile log + a monitoring counter
# ---------------------------------------------------------------------------


class _CompileLogFilter(logging.Filter):
    """Captures "Compiling <fn> ..." records, suppresses the chatter.

    Returning False drops the record before handlers AND propagation, so
    an armed run's stderr stays exactly as quiet as an unarmed one.
    """

    def filter(self, record: logging.LogRecord) -> bool:  # noqa: A003
        try:
            msg = record.getMessage()
        except Exception:  # noqa: BLE001 — a bad record is not our problem
            return True
        m = _COMPILING_RE.match(msg)
        if m is not None:
            if enabled():
                _STATE.on_compile(m.group("fn"), m.group("sig"))
            return False
        return not msg.startswith(_NOISE_PREFIXES)


_FILTER: Optional[_CompileLogFilter] = None
_PREV_LOG_COMPILES: Optional[bool] = None
_MONITORING_REGISTERED = False


def _on_duration_event(event: str, duration: float, **kw) -> None:
    """jax.monitoring belt-and-braces: counts backend compiles even if a
    jax upgrade reworded the log line the filter parses."""
    del duration, kw
    if event.endswith("/backend_compile_duration") and enabled():
        with _STATE.lock:
            _STATE.backend_compiles += 1


def _on_plain_event(event: str, **kw) -> None:
    """Persistent-compilation-cache correlation: jax fires these
    synchronously on the compiling thread right after the "Compiling <fn>"
    log line, so a hit reclassifies exactly that pending key."""
    del kw
    if not enabled():
        return
    if event == "/jax/compilation_cache/cache_hits":
        _STATE.on_cache_event(True)
    elif event == "/jax/compilation_cache/cache_misses":
        _STATE.on_cache_event(False)


def install() -> None:
    """Arm + hook (idempotent): flip ``jax_log_compiles`` on and attach
    the capture filter to the jax compile loggers."""
    global _FILTER, _PREV_LOG_COMPILES, _MONITORING_REGISTERED
    arm(True)
    if _FILTER is None:
        _FILTER = _CompileLogFilter()
        for name in _JAX_COMPILE_LOGGERS:
            logging.getLogger(name).addFilter(_FILTER)
    import jax

    if _PREV_LOG_COMPILES is None:
        _PREV_LOG_COMPILES = bool(jax.config.jax_log_compiles)
    jax.config.update("jax_log_compiles", True)
    if not _MONITORING_REGISTERED:
        try:
            jax.monitoring.register_event_duration_secs_listener(
                _on_duration_event)
            jax.monitoring.register_event_listener(_on_plain_event)
            _MONITORING_REGISTERED = True
        except Exception:  # noqa: BLE001 — the log filter alone suffices
            pass


def uninstall() -> None:
    """Detach the filter and restore ``jax_log_compiles`` (test cleanup).

    The monitoring listener stays registered (jax offers no single-listener
    removal) but is inert once disarmed.
    """
    global _FILTER, _PREV_LOG_COMPILES
    arm(None)
    if _FILTER is not None:
        for name in _JAX_COMPILE_LOGGERS:
            logging.getLogger(name).removeFilter(_FILTER)
        _FILTER = None
    if _PREV_LOG_COMPILES is not None:
        import jax

        jax.config.update("jax_log_compiles", _PREV_LOG_COMPILES)
        _PREV_LOG_COMPILES = None
