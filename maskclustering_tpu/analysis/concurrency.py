"""Concurrency family, static half: whole-program thread-safety analysis.

The pipeline is genuinely multi-threaded — prefetch daemons
(``DaemonFuture``), the overlapped executor's host-tail worker, watchdog
deadline threads (``utils/faults.call_with_deadline``), the SIGTERM
handler, two ``ThreadPoolExecutor`` pools (semantics/features.py,
ops/dbscan.py), the lock-guarded obs sinks — and, since PR 10, the
mct-serve daemon's acceptor / per-connection handler / device-worker
threads (``maskclustering_tpu/serve/``, scanned via the package root of
``SCAN_ROOTS`` and annotated with the ``# mct-thread:`` grammar below).
PR 3's registry race and PR 5's deadline/abandonment semantics were
caught by review; this module makes thread safety a machine-checked
contract, the way ``mct-check``'s other families gate the
sync/dtype/donation contracts.

**Thread-topology model.** Thread roots are collected tree-wide: targets
of ``DaemonFuture(fn)`` / ``threading.Thread(target=fn)`` / executor
``.submit(fn)`` / ``.map(fn)``, functions registered as signal handlers
(``signal.signal(SIG, fn)``), ``faults.call_with_deadline(fn, ...)``
watchdog targets, the cross-module ``THREAD_ENTRY_HINTS``, and any
function whose ``def`` line carries a ``# mct-thread: root`` marker.
Reachability closes over the module-local call graph per root, so shared
state can be attributed to the SET of roots that can touch it.

**Marker grammar** (``# mct-thread:`` — role annotations the AST alone
cannot derive)::

    # mct-thread: root                  this def is a thread entry the
                                        collector cannot see (dispatched
                                        through a registry / first-class
                                        callable)
    # mct-thread: abandon(<rationale>)  this Thread spawn is deliberately
                                        never joined (the PR-5 daemon-
                                        abandonment pattern); the
                                        rationale is REQUIRED
    # mct-thread: immutable             this module-level binding is
                                        never mutated after import

**Checks** (inline opt-out: ``# mct-ok: <CHECK>``, shared with the ast
family):

- **CONC.SHARED** — a module-level mutable reachable from >= 2 roots is
  mutated without a lock and is neither queue-typed (``deque`` /
  ``queue.Queue``: GIL-atomic mutators) nor marked immutable. The
  whole-program generalization of AST.THREADS (which stays: it fires on
  single-root mutation too, the PR-3 registry pattern).
- **CONC.LOCKORDER** — the global lock-order graph (every ``with lock:``
  body's nested acquisitions, closed over module-local calls and the
  known cross-module acquirers) must be acyclic. Nodes are the canonical
  lock ids — ``mct_lock``'s literal name when present, ``file:qualname``
  otherwise — one vocabulary with the runtime sanitizer.
- **CONC.BLOCKING** — no blocking call inside a ``with lock:`` body:
  device syncs (``np.asarray``, ``.block_until_ready()``, ``.item()``),
  file IO (``open``/``.write``/``.flush``/``.read*``, ``np.load/save``,
  ``json.dump/load``), ``time.sleep``, ``subprocess.*`` / ``os.system``,
  ``.result()`` / ``.join()`` / ``.wait()``, and acquiring a second lock
  (the order edge is additionally recorded for CONC.LOCKORDER).
- **CONC.SIGNAL** — a signal handler (transitively, module-local) may
  touch only ``threading.Event``/flag state: ``.set()``/``.is_set()``/
  ``.clear()``, ``os._exit``/``os.kill``, and plain assignments.
  Anything else — logging, IO, allocation-heavy helpers — is flagged
  (one aggregate finding per handler), because the handler can interrupt
  its own thread mid-anything.
- **CONC.JOIN** — every ``threading.Thread`` spawn is either joined with
  a bounded ``.join(timeout)`` in the same scope or carries an
  ``abandon(<rationale>)`` marker. ``with ThreadPoolExecutor(...)`` joins
  at block exit and needs nothing.
- **CONC.RESULT** — ``.result()`` with no timeout anywhere in the tree:
  an unbounded block on another thread's completion is exactly the wedge
  the PR-5 watchdogs exist to prevent (blocking-call taxonomy satellite).

Pure stdlib, no jax import — the family runs in the same sub-second
budget as the ast family.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from maskclustering_tpu.analysis.ast_checks import (
    SCAN_ROOTS,
    THREAD_ENTRY_HINTS,
    _attr_chain,
    _call_graph,
    _collect_functions,
    _is_lock_guard,
    _iter_py_files,
    _line_optout,
    _module_level_mutables,
    _MUTATOR_METHODS,
    _reachable,
    collect_thread_targets,
)
from maskclustering_tpu.analysis.findings import Finding, make_id

# ---------------------------------------------------------------------------
# the marker grammar
# ---------------------------------------------------------------------------

_MARKER_RE = re.compile(
    r"#\s*mct-thread:\s*(root|immutable|abandon)\s*(?:\(([^)]*)\))?")


def thread_markers(source_lines: Sequence[str]) -> Dict[int, Tuple[str, str]]:
    """lineno (1-based) -> (kind, argument) for every ``# mct-thread:``."""
    out: Dict[int, Tuple[str, str]] = {}
    for i, line in enumerate(source_lines, 1):
        m = _MARKER_RE.search(line)
        if m:
            out[i] = (m.group(1), (m.group(2) or "").strip())
    return out


def _marker_at(markers: Dict[int, Tuple[str, str]], node: ast.AST,
               kind: str) -> Optional[str]:
    """The marker argument when ``node``'s line carries ``kind``."""
    got = markers.get(getattr(node, "lineno", 0))
    if got and got[0] == kind:
        return got[1]
    return None


# ---------------------------------------------------------------------------
# lock identities (one vocabulary with lock_sanitizer.mct_lock)
# ---------------------------------------------------------------------------

_LOCK_CTORS = {"Lock", "RLock"}


def _lock_ctor_id(value: ast.AST, rel: str, attr: str,
                  cls: Optional[str]) -> Optional[str]:
    """Canonical id when ``value`` constructs a lock; None otherwise."""
    if not isinstance(value, ast.Call):
        return None
    chain = _attr_chain(value.func) or ""
    tail = chain.rsplit(".", 1)[-1]
    if tail == "mct_lock":
        if value.args and isinstance(value.args[0], ast.Constant) \
                and isinstance(value.args[0].value, str):
            return value.args[0].value  # the shared-vocabulary literal
        return f"{rel}:{cls + '.' if cls else ''}{attr}"
    if tail in _LOCK_CTORS and chain.split(".")[0] in ("threading", tail):
        return f"{rel}:{cls + '.' if cls else ''}{attr}"
    return None


def _collect_locks(tree: ast.Module, rel: str
                   ) -> Tuple[Dict[str, str], Dict[Tuple[str, str], str]]:
    """(module-level name -> id, (class, attr) -> id) for this module."""
    module_locks: Dict[str, str] = {}
    for stmt in tree.body:
        if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                and isinstance(stmt.targets[0], ast.Name):
            lid = _lock_ctor_id(stmt.value, rel, stmt.targets[0].id, None)
            if lid:
                module_locks[stmt.targets[0].id] = lid
    class_locks: Dict[Tuple[str, str], str] = {}
    for node in ast.walk(tree):
        if not isinstance(node, ast.ClassDef):
            continue
        for sub in ast.walk(node):
            if isinstance(sub, ast.Assign) and len(sub.targets) == 1:
                t = sub.targets[0]
                if isinstance(t, ast.Attribute) \
                        and isinstance(t.value, ast.Name) \
                        and t.value.id == "self":
                    lid = _lock_ctor_id(sub.value, rel, t.attr, node.name)
                    if lid:
                        class_locks[(node.name, t.attr)] = lid
    return module_locks, class_locks


# cross-module functions known to acquire a named lock: attribute-call
# resolution cannot follow a bound method (`metrics.count` IS
# Registry.count), so the seams are declared. Over-approximation is safe:
# a static edge that never happens only widens the graph the runtime
# sanitizer must embed into.
_METRICS_LOCK = "obs.metrics.Registry._lock"
_EVENTS_LOCK = "obs.events.EventSink._lock"
KNOWN_ACQUIRERS: Dict[str, str] = {
    "metrics.count": _METRICS_LOCK, "metrics.gauge": _METRICS_LOCK,
    "metrics.gauge_max": _METRICS_LOCK, "metrics.observe": _METRICS_LOCK,
    "metrics.count_transfer": _METRICS_LOCK,
    "obs.count": _METRICS_LOCK, "obs.gauge": _METRICS_LOCK,
    "obs.observe": _METRICS_LOCK, "obs.flush_metrics": _METRICS_LOCK,
}
# suffix-matched acquirers (any EventSink handle: `self._sink.emit`, ...)
KNOWN_ACQUIRER_SUFFIXES: Tuple[Tuple[str, str], ...] = (
    ("_sink.emit", _EVENTS_LOCK),
    ("sink.emit", _EVENTS_LOCK),
)


class _ModuleInfo:
    """Everything the checkers need from one parsed file."""

    __slots__ = ("rel", "tree", "lines", "funcs", "graph", "fn_class",
                 "markers", "module_locks", "class_locks", "mutables",
                 "queue_typed", "immutable_marked")

    def __init__(self, rel: str, tree: ast.Module, lines: List[str]):
        self.rel = rel
        self.tree = tree
        self.lines = lines
        self.funcs = _collect_functions(tree)
        self.graph = _call_graph(self.funcs)  # shared by every checker
        self.fn_class = _function_classes(tree)
        self.markers = thread_markers(lines)
        self.module_locks, self.class_locks = _collect_locks(tree, rel)
        self.mutables = _module_level_mutables(tree)
        self.queue_typed = _queue_typed_globals(tree)
        self.immutable_marked = {
            t.id
            for stmt in tree.body if isinstance(stmt, (ast.Assign,
                                                       ast.AnnAssign))
            and _marker_at(self.markers, stmt, "immutable") is not None
            for t in (stmt.targets if isinstance(stmt, ast.Assign)
                      else [stmt.target])
            if isinstance(t, ast.Name)}


def _function_classes(tree: ast.Module) -> Dict[str, Optional[str]]:
    """function bare name -> enclosing class name (for self.X lock lookup).

    Last-def-wins, matching ``_collect_functions``'s approximation.
    """
    out: Dict[str, Optional[str]] = {}

    def visit(node: ast.AST, cls: Optional[str]) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.ClassDef):
                visit(child, child.name)
            elif isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                out[child.name] = cls
                visit(child, cls)
            else:
                visit(child, cls)

    visit(tree, None)
    return out


_QUEUE_CTORS = {"deque", "Queue", "SimpleQueue", "LifoQueue",
                "PriorityQueue"}


def _queue_typed_globals(tree: ast.Module) -> Set[str]:
    """Module-level names bound to deque/Queue: their mutators are
    GIL-atomic (deque) or internally locked (queue.Queue) — the
    "queue-passed" leg of the shared-state contract."""
    out: Set[str] = set()
    for stmt in tree.body:
        if isinstance(stmt, ast.Assign) and isinstance(stmt.value, ast.Call):
            chain = _attr_chain(stmt.value.func) or ""
            if chain.rsplit(".", 1)[-1] in _QUEUE_CTORS:
                out.update(t.id for t in stmt.targets
                           if isinstance(t, ast.Name))
    return out


def _resolve_lock(expr: ast.AST, mod: _ModuleInfo, cls: Optional[str],
                  tree_module_locks: Dict[str, str]
                  ) -> Tuple[Optional[str], bool]:
    """(canonical id | None, looks-like-a-lock) for a ``with`` item or
    ``.acquire()`` receiver. Resolution order: module-local name, same-
    class ``self.X``, tree-wide unique module-level name (the
    ``faults._PLAN_LOCK`` cross-module shape), then the ``"lock" in
    chain`` heuristic (held, but anonymous in the graph)."""
    target = expr
    if isinstance(expr, ast.Call):  # lock.acquire(...) / mct_lock misuse
        target = expr.func
        if isinstance(target, ast.Attribute) and target.attr == "acquire":
            target = target.value
    chain = _attr_chain(target)
    if chain is None:
        return None, False
    parts = chain.split(".")
    if len(parts) == 1 and parts[0] in mod.module_locks:
        return mod.module_locks[parts[0]], True
    if parts[0] == "self" and len(parts) == 2 and cls \
            and (cls, parts[1]) in mod.class_locks:
        return mod.class_locks[(cls, parts[1])], True
    if parts[-1] in tree_module_locks:
        return tree_module_locks[parts[-1]], True
    return None, "lock" in chain.lower()


# ---------------------------------------------------------------------------
# acquire sets (module-local fixpoint + known cross-module seams)
# ---------------------------------------------------------------------------


def _direct_acquires(mod: _ModuleInfo, tree_module_locks: Dict[str, str]
                     ) -> Dict[str, Set[str]]:
    out: Dict[str, Set[str]] = {}
    for name, node in mod.funcs.items():
        cls = mod.fn_class.get(name)
        acquired: Set[str] = set()
        for sub in ast.walk(node):
            if isinstance(sub, ast.With):
                for item in sub.items:
                    lid, is_lock = _resolve_lock(item.context_expr, mod, cls,
                                                 tree_module_locks)
                    if is_lock and lid:
                        acquired.add(lid)
            if isinstance(sub, ast.Call):
                chain = _attr_chain(sub.func) or ""
                if chain.endswith(".acquire"):
                    lid, is_lock = _resolve_lock(sub, mod, cls,
                                                 tree_module_locks)
                    if is_lock and lid:
                        acquired.add(lid)
                if chain in KNOWN_ACQUIRERS:
                    acquired.add(KNOWN_ACQUIRERS[chain])
                else:
                    for suffix, lid in KNOWN_ACQUIRER_SUFFIXES:
                        if chain.endswith(suffix):
                            acquired.add(lid)
        out[name] = acquired
    return out


def _acquire_fixpoint(mod: _ModuleInfo, direct: Dict[str, Set[str]]
                      ) -> Dict[str, Set[str]]:
    graph = mod.graph
    acq = {name: set(locks) for name, locks in direct.items()}
    changed = True
    while changed:
        changed = False
        for name, callees in graph.items():
            for callee in callees:
                extra = acq.get(callee, set()) - acq[name]
                if extra:
                    acq[name] |= extra
                    changed = True
    return acq


# ---------------------------------------------------------------------------
# CONC.BLOCKING + lock-order edge collection (one walk serves both)
# ---------------------------------------------------------------------------

# attribute tails that block the calling thread; receivers that are string
# constants (",".join) and the path-join chains are excluded below
_BLOCKING_ATTR_TAILS = {"write", "flush", "read", "readline", "readlines",
                        "result", "join", "wait", "item",
                        "block_until_ready"}
_BLOCKING_CHAINS = {"np.asarray", "numpy.asarray", "jax.device_get",
                    "jax.block_until_ready", "time.sleep", "os.system",
                    "np.load", "np.save", "json.dump", "json.load"}
_SAFE_CHAIN_SUFFIXES = ("path.join",)


def _blocking_token(call: ast.Call) -> Optional[str]:
    chain = _attr_chain(call.func) or ""
    if chain in _BLOCKING_CHAINS:
        return chain
    if chain.startswith("subprocess."):
        return chain
    if isinstance(call.func, ast.Name) and call.func.id == "open":
        return "open"
    if isinstance(call.func, ast.Attribute) \
            and call.func.attr in _BLOCKING_ATTR_TAILS:
        if isinstance(call.func.value, ast.Constant):
            return None  # ", ".join(...) — string method, not a thread join
        if any(chain.endswith(s) for s in _SAFE_CHAIN_SUFFIXES):
            return None
        return f".{call.func.attr}"
    return None


class _LockWalkResult:
    __slots__ = ("findings", "edges")

    def __init__(self):
        self.findings: List[Finding] = []
        self.edges: Dict[Tuple[str, str], Tuple[str, int]] = {}  # -> (rel, line)


def _direct_blocking_tokens(mod: _ModuleInfo) -> Dict[str, Set[str]]:
    """function -> blocking tokens anywhere in its body (opt-out lines
    excluded so a sanctioned direct site never propagates to callers)."""
    out: Dict[str, Set[str]] = {}
    for name, node in mod.funcs.items():
        tokens: Set[str] = set()
        for sub in ast.walk(node):
            if isinstance(sub, ast.Call):
                token = _blocking_token(sub)
                if token is not None \
                        and not _line_optout(mod.lines, sub,
                                             "CONC.BLOCKING"):
                    tokens.add(token)
        out[name] = tokens
    return out


def _blocking_fixpoint(mod: _ModuleInfo) -> Dict[str, Set[str]]:
    """Transitive closure of ``_direct_blocking_tokens`` over the
    module-local call graph: calling a helper that blocks IS blocking —
    moving the IO into a function must not get it past the gate."""
    blk = _direct_blocking_tokens(mod)
    changed = True
    while changed:
        changed = False
        for name, callees in mod.graph.items():
            for callee in callees:
                extra = blk.get(callee, set()) - blk[name]
                if extra:
                    blk[name] |= extra
                    changed = True
    return blk


def _walk_locks(mod: _ModuleInfo, acq: Dict[str, Set[str]],
                blk: Dict[str, Set[str]],
                tree_module_locks: Dict[str, str],
                result: _LockWalkResult) -> None:
    """Per-function held-lock walk: blocking-call findings + order edges."""
    ordinals: Dict[Tuple[str, str], int] = {}

    def blocking_finding(fname: str, node: ast.AST, token: str,
                         held_name: str) -> None:
        if _line_optout(mod.lines, node, "CONC.BLOCKING"):
            return
        key = (fname, token)
        ordinals[key] = ordinals.get(key, 0) + 1
        result.findings.append(Finding(
            id=make_id("CONC.BLOCKING", mod.rel, fname, token,
                       ordinals[key]),
            check="CONC.BLOCKING", family="concurrency",
            message=f"{token} inside the `with {held_name}:` body of "
                    f"{fname} — a blocking call under a held lock stalls "
                    f"every thread contending for it",
            file=mod.rel, line=getattr(node, "lineno", 0)))

    def visit(node: ast.AST, fname: str, cls: Optional[str],
              held: List[Tuple[Optional[str], str]]) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            return  # nested defs walk as their own entries
        if isinstance(node, ast.With):
            new_held = list(held)
            for item in node.items:
                lid, is_lock = _resolve_lock(item.context_expr, mod, cls,
                                             tree_module_locks)
                if not is_lock:
                    continue
                display = lid or (_attr_chain(item.context_expr) or "<lock>")
                for h_id, h_disp in new_held:
                    if lid and h_id and lid != h_id:
                        result.edges.setdefault(
                            (h_id, lid),
                            (mod.rel, getattr(item.context_expr, "lineno",
                                              0)))
                    blocking_finding(fname, item.context_expr,
                                     f"lock:{display}", h_disp)
                new_held.append((lid, display))
            for child in node.body:
                visit(child, fname, cls, new_held)
            return
        if held and isinstance(node, ast.Call):
            token = _blocking_token(node)
            if token is not None:
                blocking_finding(fname, node, token, held[-1][1])
            else:
                # a module-local / known cross-module call that acquires
                # another lock under this one: an order edge + a finding.
                # A module-local callee that (transitively) blocks is a
                # blocking call too — IO moved into a helper stays caught
                chain = _attr_chain(node.func) or ""
                inner: Set[str] = set()
                if isinstance(node.func, ast.Name):
                    inner = acq.get(node.func.id, set())
                    for token in sorted(blk.get(node.func.id, ())):
                        blocking_finding(fname, node,
                                         f"{token} via {node.func.id}",
                                         held[-1][1])
                elif chain in KNOWN_ACQUIRERS:
                    inner = {KNOWN_ACQUIRERS[chain]}
                else:
                    for suffix, lid in KNOWN_ACQUIRER_SUFFIXES:
                        if chain.endswith(suffix):
                            inner = {lid}
                for lid in sorted(inner):
                    for h_id, h_disp in held:
                        if h_id and lid != h_id:
                            result.edges.setdefault(
                                (h_id, lid),
                                (mod.rel, getattr(node, "lineno", 0)))
                            blocking_finding(fname, node,
                                             f"lock:{lid} (via "
                                             f"{chain or node.func.id})",
                                             h_disp)
        for child in ast.iter_child_nodes(node):
            visit(child, fname, cls, held)

    for fname, node in mod.funcs.items():
        cls = mod.fn_class.get(fname)
        for child in ast.iter_child_nodes(node):
            visit(child, fname, cls, [])


def _find_cycles(edges: Iterable[Tuple[str, str]]) -> List[List[str]]:
    """Elementary cycles of the order graph (DFS; deduped by node set)."""
    graph: Dict[str, Set[str]] = {}
    for a, b in edges:
        graph.setdefault(a, set()).add(b)
    cycles: List[List[str]] = []
    seen_sets: Set[frozenset] = set()

    def dfs(node: str, path: List[str], on_path: Set[str]) -> None:
        for nxt in sorted(graph.get(node, ())):
            if nxt in on_path:
                cycle = path[path.index(nxt):] + [nxt]
                key = frozenset(cycle)
                if key not in seen_sets:
                    seen_sets.add(key)
                    cycles.append(cycle)
                continue
            dfs(nxt, path + [nxt], on_path | {nxt})

    for start in sorted(graph):
        dfs(start, [start], {start})
    return cycles


# ---------------------------------------------------------------------------
# CONC.SHARED — multi-root shared mutable state
# ---------------------------------------------------------------------------


def _extended_thread_roots(mod: _ModuleInfo,
                           tree_targets: Set[str],
                           tree_handlers: Set[str]) -> Set[str]:
    """This module's thread-entry function names (incl. markers)."""
    roots = {n for n in tree_targets | tree_handlers if n in mod.funcs}
    for name, node in mod.funcs.items():
        if _marker_at(mod.markers, node, "root") is not None:
            roots.add(name)
    return roots


def _accesses(node: ast.AST, names: Set[str]) -> Set[str]:
    """Module-level names from ``names`` read or written under ``node``."""
    out: Set[str] = set()
    for sub in ast.walk(node):
        if isinstance(sub, ast.Name) and sub.id in names:
            out.add(sub.id)
    return out


def check_shared_state(mod: _ModuleInfo, tree_targets: Set[str],
                       tree_handlers: Set[str]) -> List[Finding]:
    shared = mod.mutables - mod.immutable_marked - mod.queue_typed
    if not shared:
        return []
    roots = _extended_thread_roots(mod, tree_targets, tree_handlers)
    if not roots:
        return []
    reach_per_root = {r: _reachable({r}, mod.graph) for r in roots}
    thread_reachable = set().union(*reach_per_root.values())

    # which roots can touch each global? "<main>" covers module-level code
    # and every function no thread root reaches (it runs on the caller's
    # thread — almost always the main one)
    roots_touching: Dict[str, Set[str]] = {g: set() for g in shared}
    for r, reach in reach_per_root.items():
        for fname in reach:
            for g in _accesses(mod.funcs[fname], shared):
                roots_touching[g].add(r)
    for fname, node in mod.funcs.items():
        if fname not in thread_reachable:
            for g in _accesses(node, shared):
                roots_touching[g].add("<main>")
    for stmt in mod.tree.body:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            continue
        for g in _accesses(stmt, shared):
            roots_touching[g].add("<main>")

    findings: List[Finding] = []
    ordinals: Dict[Tuple[str, str], int] = {}

    def mutated_name(node: ast.AST) -> Optional[str]:
        if isinstance(node, (ast.Assign, ast.AugAssign)):
            targets = (node.targets if isinstance(node, ast.Assign)
                       else [node.target])
            for t in targets:
                base = t
                while isinstance(base, (ast.Subscript, ast.Attribute)):
                    base = base.value
                if isinstance(base, ast.Name) and base.id in shared \
                        and base is not t:
                    return base.id
        if isinstance(node, ast.Expr) and isinstance(node.value, ast.Call):
            call = node.value
            if isinstance(call.func, ast.Attribute) \
                    and call.func.attr in _MUTATOR_METHODS \
                    and isinstance(call.func.value, ast.Name) \
                    and call.func.value.id in shared:
                return call.func.value.id
        return None

    def visit(node: ast.AST, fname: str, locked: bool) -> None:
        if isinstance(node, ast.With):
            locked = locked or _is_lock_guard(node)
        name = mutated_name(node)
        if name is not None and not locked \
                and len(roots_touching[name]) >= 2 \
                and not _line_optout(mod.lines, node, "CONC.SHARED"):
            key = (fname, name)
            ordinals[key] = ordinals.get(key, 0) + 1
            findings.append(Finding(
                id=make_id("CONC.SHARED", mod.rel, fname, name,
                           ordinals[key]),
                check="CONC.SHARED", family="concurrency",
                message=f"module-level {name!r} is reachable from "
                        f"{len(roots_touching[name])} thread roots "
                        f"({', '.join(sorted(roots_touching[name]))}) and "
                        f"mutated in {fname} without a lock — guard it, "
                        f"pass it through a queue, or mark it "
                        f"`# mct-thread: immutable`",
                file=mod.rel, line=getattr(node, "lineno", 0)))
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            visit(child, fname, locked)

    for fname in sorted(thread_reachable):
        if fname not in mod.funcs:
            continue
        for child in ast.iter_child_nodes(mod.funcs[fname]):
            visit(child, fname, False)
    return findings


# ---------------------------------------------------------------------------
# CONC.SIGNAL — handlers touch only Event/flag state
# ---------------------------------------------------------------------------


def collect_signal_handlers(tree: ast.Module) -> Set[str]:
    """Function names registered via ``signal.signal(SIG, fn)``."""
    out: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            chain = _attr_chain(node.func) or ""
            if chain == "signal.signal" and len(node.args) == 2 \
                    and isinstance(node.args[1], ast.Name):
                out.add(node.args[1].id)
    return out


_SIGNAL_ALLOWED_TAILS = {"set", "is_set", "clear"}
_SIGNAL_ALLOWED_CHAINS = {"os._exit", "os.kill", "signal.signal"}
# read-only builtins that neither block, lock, nor allocate containers —
# everything else (logging, IO, json, print, dict/list construction) is
# re-entrancy surface a handler must not touch
_SIGNAL_SAFE_BUILTINS = {"isinstance", "getattr", "hasattr", "len", "id",
                         "type", "repr"}
_SIGNAL_TOKEN_CAP = 8  # aggregate message stays one readable line


def check_signal_handlers(mod: _ModuleInfo, handlers: Set[str]
                          ) -> List[Finding]:
    local = {h for h in handlers if h in mod.funcs}
    if not local:
        return []
    graph = mod.graph
    findings: List[Finding] = []
    for handler in sorted(local):
        node = mod.funcs[handler]
        if _line_optout(mod.lines, node, "CONC.SIGNAL"):
            continue
        offending: Dict[str, str] = {}  # chain -> via
        for fname in sorted(_reachable({handler}, graph)):
            via = "" if fname == handler else f" (via {fname})"
            for sub in ast.walk(mod.funcs[fname]):
                if not isinstance(sub, ast.Call):
                    continue
                chain = _attr_chain(sub.func) or ""
                tail = chain.rsplit(".", 1)[-1]
                if chain in _SIGNAL_ALLOWED_CHAINS \
                        or tail in _SIGNAL_ALLOWED_TAILS \
                        or chain in _SIGNAL_SAFE_BUILTINS:
                    continue
                if isinstance(sub.func, ast.Name) \
                        and sub.func.id in mod.funcs:
                    continue  # module-local: its body is walked itself
                offending.setdefault(chain or "<call>", via)
        if offending:
            items = sorted(offending.items())
            toks = ", ".join(f"{c}{v}" for c, v in
                             items[:_SIGNAL_TOKEN_CAP])
            if len(items) > _SIGNAL_TOKEN_CAP:
                toks += f", +{len(items) - _SIGNAL_TOKEN_CAP} more"
            findings.append(Finding(
                id=make_id("CONC.SIGNAL", mod.rel, handler),
                check="CONC.SIGNAL", family="concurrency",
                message=f"signal handler {handler} reaches beyond "
                        f"Event/flag state: {toks} — a handler interrupts "
                        f"its own thread mid-anything; set a flag and "
                        f"return",
                file=mod.rel, line=node.lineno))
    return findings


# ---------------------------------------------------------------------------
# CONC.JOIN — bounded join or an explicit abandon rationale
# ---------------------------------------------------------------------------


def _walk_own_scope(scope: ast.AST) -> Iterable[ast.AST]:
    """ast.walk that does NOT descend into nested function/class defs —
    a spawn inside a def belongs to that def's scope, not its parent's."""
    work = list(ast.iter_child_nodes(scope))
    while work:
        node = work.pop()
        yield node
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
            work.extend(ast.iter_child_nodes(node))


def check_thread_joins(mod: _ModuleInfo) -> List[Finding]:
    findings: List[Finding] = []
    ordinals: Dict[str, int] = {}

    def spawn_sites(scope: ast.AST) -> List[Tuple[ast.Call, Optional[str]]]:
        """(Thread ctor call, assigned name | None) in this scope only."""
        assigned_calls: Dict[int, str] = {}
        out: List[Tuple[ast.Call, Optional[str]]] = []
        for sub in _walk_own_scope(scope):
            if isinstance(sub, ast.Assign) \
                    and isinstance(sub.value, ast.Call) \
                    and len(sub.targets) == 1 \
                    and isinstance(sub.targets[0], ast.Name):
                chain = _attr_chain(sub.value.func) or ""
                if chain.rsplit(".", 1)[-1] == "Thread":
                    assigned_calls[id(sub.value)] = sub.targets[0].id
        for sub in _walk_own_scope(scope):
            if isinstance(sub, ast.Call):
                chain = _attr_chain(sub.func) or ""
                if chain.rsplit(".", 1)[-1] == "Thread":
                    out.append((sub, assigned_calls.get(id(sub))))
        return out

    def joins_of(scope: ast.AST) -> Dict[str, bool]:
        """name -> bounded? for every ``NAME.join(...)`` in this scope."""
        out: Dict[str, bool] = {}
        for sub in _walk_own_scope(scope):
            if isinstance(sub, ast.Call) \
                    and isinstance(sub.func, ast.Attribute) \
                    and sub.func.attr == "join" \
                    and isinstance(sub.func.value, ast.Name):
                bounded = bool(sub.args or sub.keywords)
                name = sub.func.value.id
                out[name] = out.get(name, False) or bounded
        return out

    scopes: List[Tuple[str, ast.AST]] = [("<module>", mod.tree)]
    scopes += [(name, node) for name, node in mod.funcs.items()]
    for scope_name, scope in scopes:
        joins = joins_of(scope)
        for call, assigned in spawn_sites(scope):
            rationale = _marker_at(mod.markers, call, "abandon")
            if rationale is not None:
                if not rationale.strip():
                    findings.append(Finding(
                        id=make_id("CONC.JOIN", mod.rel, scope_name,
                                   "empty-rationale"),
                        check="CONC.JOIN", family="concurrency",
                        message=f"{scope_name}: `# mct-thread: abandon()` "
                                f"needs a rationale — an empty abandonment "
                                f"is folklore, not a contract",
                        file=mod.rel, line=call.lineno))
                continue
            if assigned is not None and assigned in joins:
                if joins[assigned]:
                    continue  # bounded join
                tag = f"{assigned}-unbounded-join"
                msg = (f"{scope_name}: thread {assigned!r} is joined "
                       f"without a timeout — an unbounded join is the "
                       f"wedge the PR-5 watchdogs exist to prevent; pass "
                       f"a timeout or mark the spawn "
                       f"`# mct-thread: abandon(<why>)`")
            else:
                tag = assigned or "anonymous"
                msg = (f"{scope_name}: thread {tag!r} is spawned and never "
                       f"joined — join it with a timeout or mark the spawn "
                       f"line `# mct-thread: abandon(<why>)` (the PR-5 "
                       f"daemon-abandonment pattern, as a contract)")
            if _line_optout(mod.lines, call, "CONC.JOIN"):
                continue
            ordinals[tag] = ordinals.get(tag, 0) + 1
            findings.append(Finding(
                id=make_id("CONC.JOIN", mod.rel, scope_name, tag,
                           ordinals[tag]),
                check="CONC.JOIN", family="concurrency",
                message=msg, file=mod.rel, line=call.lineno))
    return findings


# ---------------------------------------------------------------------------
# CONC.RESULT — .result() without a timeout (blocking-call taxonomy)
# ---------------------------------------------------------------------------


def check_result_timeouts(mod: _ModuleInfo) -> List[Finding]:
    findings: List[Finding] = []
    ordinals: Dict[str, int] = {}
    scope_of: Dict[int, str] = {}
    for name, fn in mod.funcs.items():
        for sub in ast.walk(fn):
            scope_of[id(sub)] = name
    for sub in ast.walk(mod.tree):
        if not (isinstance(sub, ast.Call)
                and isinstance(sub.func, ast.Attribute)
                and sub.func.attr == "result"
                and not sub.args and not sub.keywords):
            continue
        if _line_optout(mod.lines, sub, "CONC.RESULT"):
            continue
        fname = scope_of.get(id(sub), "<module>")
        ordinals[fname] = ordinals.get(fname, 0) + 1
        findings.append(Finding(
            id=make_id("CONC.RESULT", mod.rel, fname, ordinals[fname]),
            check="CONC.RESULT", family="concurrency",
            message=f".result() without a timeout in {fname} blocks "
                    f"unboundedly on another thread — pass a timeout (the "
                    f"watchdog budgets exist for exactly this) or opt out "
                    f"with `# mct-ok: CONC.RESULT`",
            file=mod.rel, line=sub.lineno))
    return findings


# ---------------------------------------------------------------------------
# drivers
# ---------------------------------------------------------------------------


def _parse_tree(repo_root: str, roots: Sequence[str]
                ) -> Tuple[List[_ModuleInfo], List[Finding]]:
    mods: List[_ModuleInfo] = []
    findings: List[Finding] = []
    import os

    for path in _iter_py_files(repo_root, roots):
        rel = os.path.relpath(path, repo_root).replace(os.sep, "/")
        try:
            with open(path, "r", encoding="utf-8") as f:
                source = f.read()
            tree = ast.parse(source, filename=path)
        except (OSError, SyntaxError) as e:
            findings.append(Finding(
                id=make_id("CONC.PARSE", rel), check="CONC.PARSE",
                family="concurrency", message=f"could not parse: {e}",
                file=rel))
            continue
        mods.append(_ModuleInfo(rel, tree, source.splitlines()))
    return mods, findings


def _lock_walk_tree(mods: Sequence[_ModuleInfo]
                    ) -> Tuple[Set[str], _LockWalkResult]:
    """One lock walk over the parsed tree: (canonical lock ids, result).

    The single implementation behind both drivers — ``analyze_concurrency``
    keeps the blocking-call findings, ``build_lock_order_graph`` keeps the
    node/edge sets.
    """
    tree_module_locks: Dict[str, str] = {}
    for mod in mods:
        tree_module_locks.update(mod.module_locks)
    nodes: Set[str] = set(tree_module_locks.values())
    result = _LockWalkResult()
    for mod in mods:
        nodes.update(mod.class_locks.values())
        acq = _acquire_fixpoint(mod, _direct_acquires(mod,
                                                      tree_module_locks))
        _walk_locks(mod, acq, _blocking_fixpoint(mod), tree_module_locks,
                    result)
    nodes.update({_METRICS_LOCK, _EVENTS_LOCK})
    return nodes, result


def build_lock_order_graph(repo_root: str,
                           roots: Sequence[str] = SCAN_ROOTS
                           ) -> Tuple[Set[str], Set[Tuple[str, str]]]:
    """(canonical lock ids, order edges) — the static graph the runtime
    sanitizer's observed graph must embed into (lock_sanitizer.check_embeds)."""
    mods, _ = _parse_tree(repo_root, roots)
    nodes, result = _lock_walk_tree(mods)
    return nodes, set(result.edges)


def analyze_concurrency(repo_root: str,
                        roots: Sequence[str] = SCAN_ROOTS
                        ) -> List[Finding]:
    """Run the concurrency family over the tree; pure stdlib, no jax."""
    mods, findings = _parse_tree(repo_root, roots)

    # tree-wide topology: thread targets and signal handlers
    tree_targets: Set[str] = set(THREAD_ENTRY_HINTS)
    tree_handlers: Set[str] = set()
    for mod in mods:
        tree_targets |= collect_thread_targets(mod.tree)
        tree_handlers |= collect_signal_handlers(mod.tree)

    _, lock_walk = _lock_walk_tree(mods)
    for mod in mods:
        findings += check_shared_state(mod, tree_targets, tree_handlers)
        findings += check_signal_handlers(mod, tree_handlers)
        findings += check_thread_joins(mod)
        findings += check_result_timeouts(mod)
    findings += lock_walk.findings

    for cycle in _find_cycles(lock_walk.edges):
        anchor_rel, anchor_line = lock_walk.edges.get(
            (cycle[0], cycle[1]), ("", 0))
        findings.append(Finding(
            id=make_id("CONC.LOCKORDER", "+".join(sorted(set(cycle)))),
            check="CONC.LOCKORDER", family="concurrency",
            message=f"lock-order cycle {' -> '.join(cycle)} — two threads "
                    f"taking these locks in opposite orders deadlock; "
                    f"impose one global order",
            file=anchor_rel, line=anchor_line))
    return findings
