"""mct-check CLI: run the static invariant families, gate on findings.

::

    python -m maskclustering_tpu.analysis \
        [--baseline analysis_baseline.json] [--format text|json] \
        [--families ir,ast,concurrency] [--mesh SxF[xP] ...] \
        [--events out.jsonl] [--write-baseline PATH]

Exit codes: 0 clean (every finding suppressed by the baseline), 2 on any
unsuppressed finding, 1 on an analyzer crash. Stale baseline entries are
advisory (reported, never fatal) — they are the ratchet's "delete me"
signal.

Triage workflow (README "Running mct-check"): read the finding's
``file:line`` and fix it, or — for an accepted trade — add its id to
``analysis_baseline.json`` with a one-line justification.
"""

from __future__ import annotations

import argparse
import json
import logging
import os
import sys
import time
from typing import Dict, List, Optional

from maskclustering_tpu.analysis.findings import (
    DEFAULT_BASELINE,
    Finding,
    load_baseline,
    partition_findings,
    stale_in_scope,
    write_baseline,
)

log = logging.getLogger("maskclustering_tpu")


def _render_text(unsuppressed: List[Finding], suppressed: List[Finding],
                 stale: List[str], baseline_path: Optional[str],
                 elapsed_s: float) -> str:
    out = [f"== mct-check: {len(unsuppressed)} finding(s), "
           f"{len(suppressed)} suppressed, {len(stale)} stale "
           f"suppression(s) ({elapsed_s:.1f}s) =="]
    for f in unsuppressed:
        out.append(f"FAIL {f.id}")
        out.append(f"     {f.location}: {f.message}")
    if suppressed:
        out.append(f"-- suppressed by {baseline_path or 'baseline'} --")
        for f in suppressed:
            out.append(f"  ok {f.id}  ({f.location})")
    if stale:
        out.append("-- stale baseline entries (finding no longer fires; "
                   "delete them) --")
        for fid in stale:
            out.append(f"  stale {fid}")
    if not unsuppressed:
        out.append("mct-check: clean")
    return "\n".join(out)


def run_analysis(families: List[str], meshes, repo_root: str,
                 ) -> tuple:
    """(findings, analyzed fused@SxF labels | None if ir did not run,
    fused lowerings | None).

    The ir and retrace families both read the fused step's AOT texts —
    ONE ``observe_costs(keep_texts=True)`` sweep here serves both (the
    same dedup the tier-1 conftest's ``fused_lattice_aot`` fixture does
    for the tests), so ``--families ir,retrace`` pays the lattice
    compiles once.
    """
    findings: List[Finding] = []
    ir_labels = None
    lowerings = None
    # a retrace-only run over a fixture tree (no census marker) is pure
    # AST — don't pay the lattice compiles for a surface check that will
    # be skipped anyway
    retrace_needs_lowerings = "retrace" in families and os.path.exists(
        os.path.join(repo_root, "maskclustering_tpu", "analysis",
                     "retrace.py"))
    if "ir" in families or retrace_needs_lowerings:
        from maskclustering_tpu.analysis.ir_checks import (
            CANONICAL_SHAPE,
            FULL_LATTICE,
        )
        from maskclustering_tpu.obs.cost import ensure_cpu_devices, observe_costs

        ensure_cpu_devices(8)
        rows = observe_costs(tuple(meshes or FULL_LATTICE), stages=("fused",),
                             keep_texts=True, **CANONICAL_SHAPE)
        lowerings = {tuple(r["mesh"]): (r["stablehlo"], r["compiled_text"])
                     for r in rows if "stablehlo" in r}
    if "ast" in families:
        from maskclustering_tpu.analysis.ast_checks import analyze_ast

        findings += analyze_ast(repo_root)
    if "concurrency" in families:
        from maskclustering_tpu.analysis.concurrency import analyze_concurrency

        findings += analyze_concurrency(repo_root)
    if "ir" in families:
        from maskclustering_tpu.analysis.ir_checks import (
            FULL_LATTICE,
            analyze_ir,
        )

        ir_findings, rows = analyze_ir(meshes or FULL_LATTICE,
                                       repo_root=repo_root,
                                       lowerings=lowerings)
        findings += ir_findings
        ir_labels = {r["target"] for r in rows}
    if "retrace" in families:
        from maskclustering_tpu.analysis.retrace import analyze_retrace

        findings += analyze_retrace(repo_root, lowerings=lowerings,
                                    lower_missing=False)
    return findings, ir_labels, lowerings


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m maskclustering_tpu.analysis",
        description="mct-check: static IR + AST + concurrency invariant "
                    "analyzer (dtype policy, 2-sync census, donation, "
                    "collective budgets, host-sync lint, thread topology "
                    "/ lock order / signal safety)")
    p.add_argument("--baseline", default=None,
                   help=f"suppression baseline (default: {DEFAULT_BASELINE} "
                        f"at the repo root when present)")
    p.add_argument("--format", choices=("text", "json"), default="text")
    p.add_argument("--families", default="ast,ir,concurrency,retrace",
                   help="comma-subset of {ast,ir,concurrency,retrace} "
                        "(default all)")
    p.add_argument("--mesh", action="append", default=None,
                   metavar="SxF[xP]",
                   help="IR-family mesh config, repeatable (default: the "
                        "full (scene, frame) divisor lattice of 8 plus "
                        "the canonical point-sharded cell 1x2x4)")
    p.add_argument("--events", default=None,
                   help="append findings as schema-versioned 'analysis' "
                        "events to this JSONL (render with obs.report)")
    p.add_argument("--write-baseline", default=None, metavar="PATH",
                   help="write a baseline suppressing every current "
                        "finding (new entries get TODO justifications "
                        "that a human must replace)")
    p.add_argument("--write-surface", default=None, metavar="PATH",
                   help="write the compile-surface census (retrace "
                        "family) to PATH — the compile_surface_baseline"
                        ".json regeneration workflow; audit the diff "
                        "before committing")
    p.add_argument("--root", default=None,
                   help="repo root to analyze (default: auto-detected)")
    args = p.parse_args(argv)

    logging.basicConfig(level=logging.INFO, format="%(levelname)s %(message)s")
    from maskclustering_tpu.analysis.ir_checks import (
        _repo_root,
        parse_meshes,
    )

    repo_root = args.root or _repo_root()
    families = [f for f in args.families.split(",") if f]
    unknown = set(families) - {"ast", "ir", "concurrency", "retrace"}
    if unknown:
        p.error(f"unknown families {sorted(unknown)}")
    meshes = None
    if args.mesh:
        try:
            meshes = parse_meshes(args.mesh)
        except ValueError as e:
            p.error(str(e))

    baseline_path = args.baseline
    if baseline_path is None:
        default = os.path.join(repo_root, DEFAULT_BASELINE)
        baseline_path = default if os.path.exists(default) else None
    try:
        baseline = load_baseline(baseline_path)
    except (ValueError, OSError) as e:
        print(f"mct-check: bad baseline: {e}", file=sys.stderr)
        return 1

    t0 = time.perf_counter()
    try:
        findings, ir_labels, lowerings = run_analysis(families, meshes,
                                                      repo_root)
    except Exception:
        log.exception("mct-check: analyzer crashed")
        return 1
    elapsed = time.perf_counter() - t0

    if args.write_baseline:
        write_baseline(args.write_baseline, findings, baseline)
        print(f"mct-check: wrote {len(findings)} suppression(s) to "
              f"{args.write_baseline} (replace any TODO justifications)")
    if args.write_surface:
        from maskclustering_tpu.analysis.retrace import (
            compile_surface,
            fused_surface_rows,
            write_surface_baseline,
        )

        fused = fused_surface_rows(lowerings) if lowerings else None
        write_surface_baseline(args.write_surface, compile_surface(),
                               fused_rows=fused)
        print(f"mct-check: wrote the compile-surface census to "
              f"{args.write_surface}"
              + ("" if fused else " (no fused rows — run with the ir or "
                                  "retrace family to lower the lattice)"))

    unsuppressed, suppressed, stale = partition_findings(findings, baseline)
    # a family-/mesh-filtered run never re-derives the out-of-scope
    # findings; reporting their suppressions as stale would tell the user
    # to delete still-valid baseline entries
    stale = stale_in_scope(stale, families, ir_labels)

    if args.events:
        from maskclustering_tpu.obs.events import KIND_ANALYSIS, EventSink

        sink = EventSink(args.events)
        for f in findings:
            payload: Dict = f.to_json()
            payload["suppressed"] = f.id in baseline
            if f.id in baseline:
                payload["justification"] = baseline[f.id]
            sink.emit(KIND_ANALYSIS, payload)
        sink.emit(KIND_ANALYSIS, {
            "summary": True, "families": families,
            "findings": len(unsuppressed), "suppressed": len(suppressed),
            "stale": len(stale), "elapsed_s": round(elapsed, 2),
            "clean": not unsuppressed})
        sink.close()

    if args.format == "json":
        print(json.dumps({
            "clean": not unsuppressed,
            "findings": [f.to_json() for f in unsuppressed],
            "suppressed": [f.to_json() for f in suppressed],
            "stale": stale,
            "elapsed_s": round(elapsed, 2),
        }, indent=2))
    else:
        print(_render_text(unsuppressed, suppressed, stale, baseline_path,
                           elapsed))
    return 2 if unsuppressed else 0


if __name__ == "__main__":
    sys.exit(main())
