"""Visualization & debug-image subsystem (reference visualize/*, get_top_images.py).

Host-side artifact writers plus one genuinely hot op — z-buffered point
splatting for object re-projection — which runs as a jitted JAX
scatter-min instead of the reference's per-point Python loop
(get_top_images.py:137-169).
"""

from maskclustering_tpu.visualize.scene import (  # noqa: F401
    instance_palette,
    vis_scene,
)
from maskclustering_tpu.visualize.mask2d import (  # noqa: F401
    colorize_id_map,
    create_colormap,
    vis_mask_frame,
    frames_to_gif,
)
from maskclustering_tpu.visualize.top_images import (  # noqa: F401
    project_zbuffer,
    bbox_by_projection,
    draw_bbox,
    save_debug_grids,
)
from maskclustering_tpu.visualize.debug_viewers import (  # noqa: F401
    compare_mask_dirs,
    depth_preview,
    fused_cloud_preview,
)
