"""Headless debug viewers for preprocessed scenes.

Covers the reference's three remaining tasmap debug tools (SURVEY.md §2.1
"misc tasmap debug viewers") without an interactive Open3D window — outputs
are files, usable over SSH on a TPU-VM:

- depth_preview: per-frame backprojected depth cloud to PLY + a colormapped
  depth PNG (reference tasmap/vis_depth.py:127-148 streams the same clouds
  into an o3d window);
- compare_mask_dirs: stacked side-by-side composite per common frame of two
  mask-visualization directories, separated by a black rule (reference
  tasmap/compare_masks.py);
- fused_cloud_preview: strided fusion of backprojected RGB-D frames with a
  per-frame point cap, written as a colored PLY (reference
  tasmap/visualize_preprocessed.py:54-105).

All three operate on the dataset duck-type (get_depth / get_rgb /
get_intrinsics / get_extrinsic / get_frame_list) so they work for any
registered dataset, not just tasmap.
"""

from __future__ import annotations

import os
from typing import List, Optional, Sequence

import numpy as np

from maskclustering_tpu.io.ply import write_ply_points


def _backproject_frame(dataset, frame_id, max_points: Optional[int] = None,
                       rng: Optional[np.random.Generator] = None):
    """(points (M, 3), colors (M, 3) uint8) of one frame's valid depth."""
    from maskclustering_tpu.ops.geometry import backproject_depth_np

    depth = np.asarray(dataset.get_depth(frame_id), dtype=np.float64)
    intr = np.asarray(dataset.get_intrinsics(frame_id), dtype=np.float64)
    c2w = np.asarray(dataset.get_extrinsic(frame_id), dtype=np.float64)
    rgb = np.asarray(dataset.get_rgb(frame_id))
    h, w = depth.shape
    if rgb.shape[:2] != (h, w):
        from maskclustering_tpu.io.image import resize_nearest

        rgb = resize_nearest(rgb, (w, h))
    if not np.all(np.isfinite(c2w)):
        return np.zeros((0, 3)), np.zeros((0, 3), np.uint8)
    pts, ok = backproject_depth_np(depth, intr, c2w)
    cols = rgb[ok]
    if max_points is not None and len(pts) > max_points:
        rng = rng or np.random.default_rng(0)
        idx = rng.choice(len(pts), max_points, replace=False)
        pts, cols = pts[idx], cols[idx]
    return pts, cols


def depth_preview(dataset, frame_id, out_dir: str) -> List[str]:
    """One frame's depth as a colormapped PNG + backprojected PLY."""
    from PIL import Image

    os.makedirs(out_dir, exist_ok=True)
    depth = np.asarray(dataset.get_depth(frame_id), dtype=np.float64)
    dmax = float(depth.max()) or 1.0
    norm = np.clip(depth / dmax, 0.0, 1.0)
    # simple turbo-ish ramp: near = warm, far = cold, invalid = black
    r = np.clip(1.5 - np.abs(2.0 * norm - 0.5) * 2.0, 0, 1)
    g = np.clip(1.5 - np.abs(2.0 * norm - 1.0) * 2.0, 0, 1)
    b = np.clip(1.5 - np.abs(2.0 * norm - 1.5) * 2.0, 0, 1)
    img = (np.stack([r, g, b], axis=-1) * 255).astype(np.uint8)
    img[depth <= 0] = 0
    png_path = os.path.join(out_dir, f"depth_{frame_id}.png")
    Image.fromarray(img).save(png_path)

    pts, cols = _backproject_frame(dataset, frame_id)
    ply_path = os.path.join(out_dir, f"depth_{frame_id}.ply")
    write_ply_points(ply_path, pts.astype(np.float32), cols)
    return [png_path, ply_path]


def compare_mask_dirs(dir_a: str, dir_b: str, out_dir: str,
                      separator_height: int = 2) -> List[str]:
    """Stack same-named images from two directories with a black rule."""
    from PIL import Image

    import logging

    os.makedirs(out_dir, exist_ok=True)
    common = sorted(set(os.listdir(dir_a)) & set(os.listdir(dir_b)))
    written = []
    for name in common:
        try:
            a = Image.open(os.path.join(dir_a, name)).convert("RGB")
            b = Image.open(os.path.join(dir_b, name)).convert("RGB")
        except Exception:  # stray non-image entries must not abort the compare
            logging.getLogger("maskclustering_tpu").debug(
                "compare_mask_dirs: skipping non-image entry %r", name)
            continue
        out = Image.new("RGB", (max(a.width, b.width),
                                a.height + separator_height + b.height),
                        (0, 0, 0))
        out.paste(a, (0, 0))
        out.paste(b, (0, a.height + separator_height))
        path = os.path.join(out_dir, name)
        out.save(path)
        written.append(path)
    return written


def fused_cloud_preview(dataset, out_path: str, stride: int = 1,
                        max_points_per_frame: int = 200_000,
                        frame_ids: Optional[Sequence] = None) -> str:
    """Fuse strided backprojected RGB-D frames into one colored PLY."""
    rng = np.random.default_rng(0)
    ids = list(frame_ids) if frame_ids is not None else dataset.get_frame_list(stride)
    all_pts, all_cols = [], []
    for fid in ids:
        pts, cols = _backproject_frame(dataset, fid,
                                       max_points=max_points_per_frame, rng=rng)
        all_pts.append(pts)
        all_cols.append(cols)
    pts = np.concatenate(all_pts) if all_pts else np.zeros((0, 3))
    cols = np.concatenate(all_cols) if all_cols else np.zeros((0, 3), np.uint8)
    os.makedirs(os.path.dirname(out_path) or ".", exist_ok=True)
    write_ply_points(out_path, pts.astype(np.float32), cols)
    return out_path
