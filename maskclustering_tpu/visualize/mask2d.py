"""2D mask id-map visualization (reference visualize/vis_mask.py) + frame
sequences to GIF (reference tasmap/vis_masks_to_mp4.py, ffmpeg-free).
"""

from __future__ import annotations

import os
from typing import List, Optional, Sequence

import numpy as np

from maskclustering_tpu.io.image import resize_nearest


def create_colormap(num: int = 65536, seed: int = 1) -> np.ndarray:
    """(num,3) uint8 colormap; index 0 is black (vis_mask.py create_colormap)."""
    rng = np.random.default_rng(seed)
    cmap = rng.integers(0, 256, size=(num, 3)).astype(np.uint8)
    cmap[0] = 0
    return cmap


def colorize_id_map(seg: np.ndarray, colormap: Optional[np.ndarray] = None) -> np.ndarray:
    """Vectorised palette lookup: id-map (H,W) -> (H,W,3) uint8."""
    seg = np.asarray(seg)
    if colormap is None:
        colormap = create_colormap(int(seg.max()) + 1)
    return colormap[np.minimum(seg.astype(np.int64), len(colormap) - 1)]


def _draw_label(img: np.ndarray, text: str, center) -> None:
    """Stamp the mask id at its centroid (vis_mask.py:33-35); cv2 when
    present, PIL fallback."""
    try:
        import cv2

        cv2.putText(img, text, center, cv2.FONT_HERSHEY_SIMPLEX, 1, (0, 0, 0), 2)
    except Exception:
        from PIL import Image, ImageDraw

        pil = Image.fromarray(img)
        ImageDraw.Draw(pil).text(center, text, fill=(0, 0, 0))
        img[:] = np.asarray(pil)


def vis_mask_frame(dataset, frame_id, vis_dir: str,
                   colormap: Optional[np.ndarray] = None) -> str:
    """Colorized id-map side by side with the RGB frame, half scale.

    Matches reference vis_mask.py:17-39: per-mask color + id text at the
    mask centroid, concatenated horizontally with the raw RGB and
    downscaled 2x. Returns the written path.
    """
    seg = dataset.get_segmentation(frame_id, align_with_depth=False)
    color_seg = colorize_id_map(seg, colormap).copy()
    for mask_id in np.unique(seg):
        if mask_id == 0:
            continue
        ys, xs = np.nonzero(seg == mask_id)
        _draw_label(color_seg, str(int(mask_id)),
                    (int(xs.mean()), int(ys.mean())))
    rgb = dataset.get_rgb(frame_id)
    if rgb.shape[:2] != color_seg.shape[:2]:
        color_seg = resize_nearest(color_seg, (rgb.shape[1], rgb.shape[0]))
    combined = np.concatenate([rgb, color_seg], axis=1)
    combined = combined[::2, ::2]  # half scale (vis_mask.py:38)
    os.makedirs(vis_dir, exist_ok=True)
    path = os.path.join(vis_dir, f"{frame_id}.png")
    from PIL import Image

    Image.fromarray(combined).save(path)
    return path


def frames_to_gif(image_paths: Sequence[str], out_path: str,
                  fps: int = 10) -> str:
    """Stitch frame PNGs into an animated GIF.

    The reference pipes mask overlays through imageio to mp4/gif
    (tasmap/vis_masks_to_mp4.py); GIF via PIL needs no codec stack.
    """
    from PIL import Image

    rgb_frames = [Image.open(p).convert("RGB") for p in image_paths]
    if not rgb_frames:
        raise ValueError("no frames to animate")
    # GIF honors only the first frame's palette: quantize every frame against
    # one shared adaptive palette or later frames render with wrong colors
    first = rgb_frames[0].quantize(colors=256)
    frames: List[Image.Image] = [first]
    frames += [f.quantize(palette=first) for f in rgb_frames[1:]]
    os.makedirs(os.path.dirname(out_path) or ".", exist_ok=True)
    frames[0].save(out_path, save_all=True, append_images=frames[1:],
                   duration=max(1, int(1000 / fps)), loop=0)
    return out_path
