"""Debug images: object re-projection with z-buffered splatting + 3x3 grids.

Reference get_top_images.py projects each object's point cloud into its
top frames with a per-point Python z-buffer loop (get_top_images.py:137-169),
draws a red bbox, and stitches 3x3 matplotlib grids (317-352, 286-313).
Here the splatting is one jitted scatter-min over the pixel grid — the
per-point loop becomes two vectorised scatters — and grids are plain PIL
pastes (no matplotlib/display needed on a TPU host).
"""

from __future__ import annotations

import os
from functools import partial
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from maskclustering_tpu.ops.geometry import invert_se3


@partial(jax.jit, static_argnames=("height", "width"))
def project_zbuffer(
    points: jnp.ndarray,  # (N,3) world
    colors: jnp.ndarray,  # (N,3) float in [0,1]
    intrinsics: jnp.ndarray,  # (3,3)
    cam_to_world: jnp.ndarray,  # (4,4)
    height: int,
    width: int,
):
    """Splat points into a (H,W,3) image with z-buffering.

    Returns (image uint8, zbuffer f32 (inf where empty), visible bool (N,)).
    The reference walks points serially updating a z-buffer
    (get_top_images.py:147-169); the scatter formulation computes the same
    front-most surface: scatter-min depths per pixel. Depth ties are broken
    by a scatter-max over the RGB packed into ONE comparable integer, so a
    single point's color wins wholesale — no cross-point channel blending.
    """
    world_to_cam = invert_se3(cam_to_world)
    cam = points @ world_to_cam[:3, :3].T + world_to_cam[:3, 3]
    z = cam[:, 2]
    fx, fy = intrinsics[0, 0], intrinsics[1, 1]
    cx, cy = intrinsics[0, 2], intrinsics[1, 2]
    safe_z = jnp.where(z > 1e-6, z, 1.0)
    px = jnp.round(fx * cam[:, 0] / safe_z + cx).astype(jnp.int32)
    py = jnp.round(fy * cam[:, 1] / safe_z + cy).astype(jnp.int32)
    valid = (z > 1e-6) & (px >= 0) & (px < width) & (py >= 0) & (py < height)
    # invalid points go to a dump slot past the image
    lin = jnp.where(valid, py * width + px, height * width)
    zbuf = jnp.full(height * width + 1, jnp.inf, dtype=jnp.float32)
    zbuf = zbuf.at[lin].min(jnp.where(valid, z, jnp.inf).astype(jnp.float32))
    visible = valid & (z.astype(jnp.float32) <= zbuf[lin])
    rgb8 = jnp.clip((colors * 255.0).astype(jnp.int32), 0, 255)
    code = (rgb8[:, 0] << 16) | (rgb8[:, 1] << 8) | rgb8[:, 2]
    codebuf = jnp.zeros(height * width + 1, dtype=jnp.int32)
    codebuf = codebuf.at[lin].max(jnp.where(visible, code, 0))
    flat = codebuf[:height * width]
    image = jnp.stack([(flat >> 16) & 0xFF, (flat >> 8) & 0xFF, flat & 0xFF],
                      axis=-1).astype(jnp.uint8).reshape(height, width, 3)
    return image, zbuf[:height * width].reshape(height, width), visible


def bbox_by_projection(points: np.ndarray, intrinsics: np.ndarray,
                       cam_to_world: np.ndarray, image_hw: Tuple[int, int]
                       ) -> Optional[Tuple[int, int, int, int]]:
    """(px_min, py_min, px_max, py_max) of the object's visible pixels, or
    None when nothing projects into the frame (get_top_images.py:171-177)."""
    h, w = image_hw
    pts = jnp.asarray(points, dtype=jnp.float32)
    _, zbuf, _ = project_zbuffer(pts, jnp.zeros_like(pts),
                                 jnp.asarray(intrinsics, dtype=jnp.float32),
                                 jnp.asarray(cam_to_world, dtype=jnp.float32),
                                 h, w)
    filled = np.isfinite(np.asarray(zbuf))
    if not filled.any():
        return None
    ys, xs = np.nonzero(filled)
    return int(xs.min()), int(ys.min()), int(xs.max()), int(ys.max())


def draw_bbox(rgb: np.ndarray, bbox: Optional[Tuple[int, int, int, int]],
              color=(255, 0, 0), thickness: int = 4) -> np.ndarray:
    """Red rectangle on a copy of the image (get_top_images.py draw_red_bbox)."""
    out = np.asarray(rgb).copy()
    if bbox is None:
        return out
    h, w = out.shape[:2]
    x0, y0, x1, y1 = (int(v) for v in bbox)
    x0, x1 = np.clip([x0, x1], 0, w - 1)
    y0, y1 = np.clip([y0, y1], 0, h - 1)
    t = thickness
    out[max(0, y0 - t // 2):y0 + t, x0:x1 + 1] = color
    out[max(0, y1 - t // 2):min(h, y1 + t), x0:x1 + 1] = color
    out[y0:y1 + 1, max(0, x0 - t // 2):x0 + t] = color
    out[y0:y1 + 1, max(0, x1 - t // 2):min(w, x1 + t)] = color
    return out


def stitch_grid(images: Sequence[np.ndarray], cell: int = 512,
                cols: int = 3) -> np.ndarray:
    """Up-to-3x3 black-background grid (get_top_images.py:286-313)."""
    from PIL import Image

    n = min(cols * cols, len(images))
    if n == 0:
        return np.zeros((cell, cell, 3), dtype=np.uint8)
    rows = int(np.ceil(n / cols))
    use_cols = cols if n > 1 else 1
    canvas = np.zeros((rows * cell, use_cols * cell, 3), dtype=np.uint8)
    for i in range(n):
        r, c = divmod(i, cols)
        im = Image.fromarray(np.asarray(images[i])).resize((cell, cell))
        canvas[r * cell:(r + 1) * cell, c * cell:(c + 1) * cell] = np.asarray(im)
    return canvas


def save_debug_grids(
    dataset,
    object_dict: Dict[int, dict],
    scene_points: np.ndarray,
    save_root_dir: str,
    max_objects: Optional[int] = None,
) -> List[str]:
    """Per object: bbox images for its representative frames + a 3x3 grid.

    object_dict is the clustering artifact {idx: {point_ids, mask_list,
    repre_mask_list}} (models/postprocess.export_artifacts); each
    repre_mask entry is (frame_id, mask_id, coverage), mirroring
    get_top_images.save_debug_image's inputs. Returns grid paths.
    """
    from PIL import Image

    grid_dir = os.path.join(save_root_dir, "grid")
    bbox_dir = os.path.join(save_root_dir, "bbox")
    os.makedirs(grid_dir, exist_ok=True)
    os.makedirs(bbox_dir, exist_ok=True)
    scene_points = np.asarray(scene_points)
    grids = []
    keys = sorted(object_dict.keys())
    if max_objects is not None:
        keys = keys[:max_objects]
    for key in keys:
        entry = object_dict[key]
        obj_points = scene_points[np.asarray(entry["point_ids"], dtype=np.int64)]
        images = []
        for frame_id, mask_id, conf in entry.get("repre_mask_list", []):
            rgb = dataset.get_rgb(frame_id)
            intr = dataset.get_intrinsics(frame_id)
            extr = dataset.get_extrinsic(frame_id)
            bbox = bbox_by_projection(obj_points, intr, extr, rgb.shape[:2])
            bbox_image = draw_bbox(rgb, bbox)
            images.append(bbox_image)
            fname = f"{key}_{float(conf):.3f}_{frame_id}_.png"
            Image.fromarray(bbox_image).save(os.path.join(bbox_dir, fname))
        grid_path = os.path.join(grid_dir, f"{key}.png")
        Image.fromarray(stitch_grid(images)).save(grid_path)
        grids.append(grid_path)
    return grids
