"""3D scene visualization: instance-colored clouds (reference visualize/vis_scene.py).

The reference renders through pyviz3d / Open3D windows (vis_scene.py:20-62,
vis_scene_with_o3d.py:22-77). Headless TPU hosts have neither a display
nor Open3D, so the portable artifact is colored PLY files (any viewer
opens them); when pyviz3d happens to be importable the same data is also
exported as its interactive HTML bundle.
"""

from __future__ import annotations

import os
from typing import Dict, Optional

import numpy as np

from maskclustering_tpu.io.ply import write_ply_points


def instance_palette(num: int, seed: int = 0) -> np.ndarray:
    """(num,3) uint8 deterministic distinct-ish colors (vis_one_object's
    random color draw, made reproducible)."""
    rng = np.random.default_rng(seed)
    return rng.integers(40, 255, size=(num, 3)).astype(np.uint8)


def vis_scene(
    scene_points: np.ndarray,
    pred_masks: np.ndarray,
    out_dir: str,
    scene_colors: Optional[np.ndarray] = None,
    point_size: int = 20,
    seed: int = 0,
) -> Dict[str, str]:
    """Write instance-colored scene artifacts; returns {name: path}.

    pred_masks is the (N_points, N_instances) bool matrix from the
    prediction npz (reference vis_scene.py:38-41). Outputs:
    ``instances.ply`` (labeled points only, one color per instance),
    ``rgb.ply`` (tone-mapped scan colors, if given; vis_scene.py:29-31),
    and a pyviz3d bundle when that package is importable.
    """
    scene_points = np.asarray(scene_points, dtype=np.float64)
    centered = scene_points - scene_points.mean(axis=0)
    pred_masks = np.asarray(pred_masks, dtype=bool)
    num_instances = pred_masks.shape[1] if pred_masks.ndim == 2 else 0
    palette = instance_palette(num_instances, seed)

    instance_colors = np.zeros((len(centered), 3), dtype=np.uint8)
    labels, centers = [], []
    for idx in range(num_instances):
        member = pred_masks[:, idx]
        instance_colors[member] = palette[idx]
        labels.append(str(idx))
        centers.append(centered[member].mean(axis=0) if member.any()
                       else np.zeros(3))

    os.makedirs(out_dir, exist_ok=True)
    out: Dict[str, str] = {}
    labeled = instance_colors.sum(axis=1) != 0
    inst_path = os.path.join(out_dir, "instances.ply")
    write_ply_points(inst_path, centered[labeled], instance_colors[labeled])
    out["instances"] = inst_path

    if scene_colors is not None:
        colors = np.asarray(scene_colors, dtype=np.float64)
        if colors.max(initial=0.0) > 1.0:
            colors = colors / 255.0
        # brighten the raw scan by gamma tone mapping (vis_scene.py:30)
        toned = (np.power(colors, 1 / 2.2) * 255).astype(np.uint8)
        rgb_path = os.path.join(out_dir, "rgb.ply")
        write_ply_points(rgb_path, centered, toned)
        out["rgb"] = rgb_path

    try:  # optional interactive bundle, never required
        import pyviz3d.visualizer as viz  # type: ignore

        v = viz.Visualizer()
        v.add_points("Instances", centered[labeled],
                     instance_colors[labeled].astype(np.float64),
                     visible=True, point_size=point_size)
        if scene_colors is not None:
            v.add_points("RGB", centered, toned.astype(np.float64),
                         visible=False, point_size=point_size)
        if labels:
            v.add_labels("Labels", labels, centers,
                         [palette[i].astype(np.float64) for i in range(num_instances)])
        v.save(os.path.join(out_dir, "pyviz3d"))
        out["pyviz3d"] = os.path.join(out_dir, "pyviz3d")
    except Exception:
        pass
    return out
