"""Host-side image decode helpers (I/O layer, not compute).

Depth PNGs are 16-bit; segmentation id-maps are uint8/uint16 PNGs where the
resize to depth resolution must be INTER_NEAREST to keep ids intact
(reference dataset/scannet.py:66-73). cv2 is used when present for exact
INTER_NEAREST alignment; PIL is the fallback.
"""

from __future__ import annotations

import numpy as np

try:
    import cv2

    _HAS_CV2 = True
except Exception:  # pragma: no cover
    cv2 = None
    _HAS_CV2 = False

from PIL import Image


def read_depth_png(path: str, depth_scale: float = 1000.0) -> np.ndarray:
    """Read a 16-bit depth PNG and convert to metres (float32).

    The conversion is computed as ``raw.astype(f32) * f32(1/scale)`` — the
    exact operation the device-feed codec (io/feed.py) replays after a
    uint16 upload, so the compact-feed path is bit-identical to loading
    f32 on host (IEEE-754 f32 multiplication is deterministic).

    Deliberate deviation from the reference decode: the reference divides in
    float64 then truncates (``(raw / scale).astype(f32)``, reference
    dataset/scannet.py depth load). The two differ by 1 ulp for ~59% of
    uint16 values (measured over the full range), i.e. sub-micrometre at
    ScanNet's 1 mm quantization — irrelevant next to sensor noise, but any
    golden fixture derived from the old float64-division loader will not
    bit-match this one.
    """
    if _HAS_CV2:
        raw = cv2.imread(path, cv2.IMREAD_UNCHANGED)
        if raw is None:
            raise FileNotFoundError(path)
    else:
        raw = np.asarray(Image.open(path))
    return raw.astype(np.float32) * np.float32(1.0 / depth_scale)


def read_rgb(path: str) -> np.ndarray:
    """Read an RGB image as (H,W,3) uint8 in RGB channel order."""
    if _HAS_CV2:
        bgr = cv2.imread(path)
        if bgr is None:
            raise FileNotFoundError(path)
        return cv2.cvtColor(bgr, cv2.COLOR_BGR2RGB)
    return np.asarray(Image.open(path).convert("RGB"))


def read_mask_png(path: str) -> np.ndarray:
    """Read a segmentation id-map PNG unchanged (uint8 or uint16)."""
    if _HAS_CV2:
        seg = cv2.imread(path, cv2.IMREAD_UNCHANGED)
        if seg is None:
            raise FileNotFoundError(path)
        return seg
    return np.asarray(Image.open(path))


def write_mask_png(path: str, ids: np.ndarray) -> None:
    ids = np.asarray(ids)
    if ids.max(initial=0) > 255:
        ids = ids.astype(np.uint16)
    else:
        ids = ids.astype(np.uint8)
    Image.fromarray(ids).save(path)


def write_depth_png(path: str, depth_mm: np.ndarray) -> None:
    """Write a 16-bit depth PNG (values in millimetres, uint16).

    The reference writes exported depth frames as 16-bit PNGs via pypng
    (preprocess/scannet/SensorData.py export_depth_images); PIL 'I;16'
    produces the same on-disk format.
    """
    depth_mm = np.asarray(depth_mm)
    if depth_mm.dtype != np.uint16:
        depth_mm = np.clip(np.round(depth_mm), 0, 65535).astype(np.uint16)
    if _HAS_CV2:
        cv2.imwrite(path, depth_mm)
    else:
        # uint16 maps to I;16 via PIL's typemap; the explicit mode= kwarg
        # is deprecated (removed in Pillow 13)
        Image.fromarray(depth_mm).save(path)


def resize_nearest(img: np.ndarray, size_wh: tuple[int, int]) -> np.ndarray:
    """Nearest-neighbor resize to (width, height), id-preserving.

    Matches cv2.resize(..., interpolation=cv2.INTER_NEAREST) semantics, which
    is what aligns segmentation maps with depth maps in the reference
    (dataset/scannet.py:71-72).
    """
    w, h = size_wh
    if img.shape[0] == h and img.shape[1] == w:
        return img
    if _HAS_CV2:
        return cv2.resize(img, (w, h), interpolation=cv2.INTER_NEAREST)
    # cv2 INTER_NEAREST samples src_idx = floor(dst_idx * scale)
    sy = img.shape[0] / h
    sx = img.shape[1] / w
    yi = np.minimum((np.arange(h) * sy).astype(np.int64), img.shape[0] - 1)
    xi = np.minimum((np.arange(w) * sx).astype(np.int64), img.shape[1] - 1)
    return img[yi[:, None], xi[None, :]]
