"""Minimal self-contained PLY point-cloud I/O.

The reference reads scene clouds through Open3D's C++ PLY reader
(reference dataset/scannet.py:87-90). Open3D is not a dependency here, so
this module implements the subset of PLY needed by the datasets: vertex
positions (+ optional colors) in binary-little-endian or ascii format.
"""

from __future__ import annotations

import numpy as np

_PLY_TO_NP = {
    "char": "i1", "int8": "i1",
    "uchar": "u1", "uint8": "u1",
    "short": "i2", "int16": "i2",
    "ushort": "u2", "uint16": "u2",
    "int": "i4", "int32": "i4",
    "uint": "u4", "uint32": "u4",
    "float": "f4", "float32": "f4",
    "double": "f8", "float64": "f8",
}


def _parse_header(f):
    """Parse a PLY header. Returns (format, elements, header_end_offset).

    elements is a list of (name, count, [(prop_name, np_dtype_str), ...]).
    List properties (e.g. face vertex_indices) are recorded with dtype None
    and a (count_type, item_type) tuple instead.
    """
    magic = f.readline().strip()
    if magic != b"ply":
        raise ValueError("not a PLY file")
    fmt = None
    elements = []
    while True:
        line = f.readline()
        if not line:
            raise ValueError("unexpected EOF in PLY header")
        tokens = line.decode("ascii", errors="replace").strip().split()
        if not tokens or tokens[0] == "comment" or tokens[0] == "obj_info":
            continue
        if tokens[0] == "format":
            fmt = tokens[1]
        elif tokens[0] == "element":
            elements.append((tokens[1], int(tokens[2]), []))
        elif tokens[0] == "property":
            if tokens[1] == "list":
                elements[-1][2].append((tokens[4], None, (_PLY_TO_NP[tokens[2]], _PLY_TO_NP[tokens[3]])))
            else:
                elements[-1][2].append((tokens[2], _PLY_TO_NP[tokens[1]], None))
        elif tokens[0] == "end_header":
            break
    return fmt, elements


def read_ply_points(path: str, return_colors: bool = False):
    """Read vertex x/y/z (and optionally r/g/b) from a PLY file.

    Returns (N,3) float64 positions, or a (positions, colors_uint8) tuple.
    """
    with open(path, "rb") as f:
        fmt, elements = _parse_header(f)
        endian = "<" if fmt in ("binary_little_endian", "ascii") else ">"
        verts = None
        colors = None
        for name, count, props in elements:
            has_list = any(p[1] is None for p in props)
            if fmt == "ascii":
                if name == "vertex":
                    names = [p[0] for p in props]
                    rows = [f.readline().split() for _ in range(count)]
                    arr = np.array(rows, dtype=np.float64)
                    ix = [names.index(c) for c in ("x", "y", "z")]
                    verts = arr[:, ix]
                    if return_colors and all(c in names for c in ("red", "green", "blue")):
                        ic = [names.index(c) for c in ("red", "green", "blue")]
                        colors = arr[:, ic].astype(np.uint8)
                else:
                    for _ in range(count):
                        f.readline()
            else:
                if has_list:
                    # ragged element (faces): must walk it item by item to skip
                    for _ in range(count):
                        for _, dt, list_dt in props:
                            if dt is None:
                                ct, it = list_dt
                                n = int(np.frombuffer(f.read(np.dtype(ct).itemsize), dtype=endian + ct)[0])
                                f.read(n * np.dtype(it).itemsize)
                            else:
                                f.read(np.dtype(dt).itemsize)
                    continue
                dtype = np.dtype([(p[0], endian + p[1]) for p in props])
                data = np.frombuffer(f.read(count * dtype.itemsize), dtype=dtype)
                if name == "vertex":
                    verts = np.stack([data["x"], data["y"], data["z"]], axis=1).astype(np.float64)
                    if return_colors and all(c in dtype.names for c in ("red", "green", "blue")):
                        colors = np.stack([data["red"], data["green"], data["blue"]], axis=1).astype(np.uint8)
    if verts is None:
        raise ValueError(f"no vertex element found in {path}")
    if return_colors:
        return verts, colors
    return verts


def write_ply_points(path: str, points: np.ndarray, colors: np.ndarray | None = None) -> None:
    """Write an (N,3) point cloud as binary-little-endian PLY."""
    points = np.asarray(points, dtype=np.float32)
    n = len(points)
    fields = [("x", "<f4"), ("y", "<f4"), ("z", "<f4")]
    if colors is not None:
        fields += [("red", "u1"), ("green", "u1"), ("blue", "u1")]
    rec = np.empty(n, dtype=np.dtype(fields))
    rec["x"], rec["y"], rec["z"] = points[:, 0], points[:, 1], points[:, 2]
    if colors is not None:
        colors = np.asarray(colors, dtype=np.uint8)
        rec["red"], rec["green"], rec["blue"] = colors[:, 0], colors[:, 1], colors[:, 2]
    header = ["ply", "format binary_little_endian 1.0", f"element vertex {n}"]
    header += [f"property float {c}" for c in ("x", "y", "z")]
    if colors is not None:
        header += [f"property uchar {c}" for c in ("red", "green", "blue")]
    header.append("end_header")
    with open(path, "wb") as f:
        f.write(("\n".join(header) + "\n").encode("ascii"))
        f.write(rec.tobytes())
