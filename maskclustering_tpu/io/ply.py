"""Minimal self-contained PLY point-cloud I/O.

The reference reads scene clouds through Open3D's C++ PLY reader
(reference dataset/scannet.py:87-90). Open3D is not a dependency here, so
this module implements the subset of PLY needed by the datasets: vertex
positions (+ optional colors) in binary-little-endian or ascii format.
"""

from __future__ import annotations

import numpy as np

_PLY_TO_NP = {
    "char": "i1", "int8": "i1",
    "uchar": "u1", "uint8": "u1",
    "short": "i2", "int16": "i2",
    "ushort": "u2", "uint16": "u2",
    "int": "i4", "int32": "i4",
    "uint": "u4", "uint32": "u4",
    "float": "f4", "float32": "f4",
    "double": "f8", "float64": "f8",
}


def _parse_header(f):
    """Parse a PLY header. Returns (format, elements, header_end_offset).

    elements is a list of (name, count, [(prop_name, np_dtype_str), ...]).
    List properties (e.g. face vertex_indices) are recorded with dtype None
    and a (count_type, item_type) tuple instead.
    """
    magic = f.readline().strip()
    if magic != b"ply":
        raise ValueError("not a PLY file")
    fmt = None
    elements = []
    while True:
        line = f.readline()
        if not line:
            raise ValueError("unexpected EOF in PLY header")
        tokens = line.decode("ascii", errors="replace").strip().split()
        if not tokens or tokens[0] == "comment" or tokens[0] == "obj_info":
            continue
        if tokens[0] == "format":
            fmt = tokens[1]
        elif tokens[0] == "element":
            elements.append((tokens[1], int(tokens[2]), []))
        elif tokens[0] == "property":
            if tokens[1] == "list":
                elements[-1][2].append((tokens[4], None, (_PLY_TO_NP[tokens[2]], _PLY_TO_NP[tokens[3]])))
            else:
                elements[-1][2].append((tokens[2], _PLY_TO_NP[tokens[1]], None))
        elif tokens[0] == "end_header":
            break
    return fmt, elements


def read_ply_points(path: str, return_colors: bool = False):
    """Read vertex x/y/z (and optionally r/g/b) from a PLY file.

    Returns (N,3) float64 positions, or a (positions, colors_uint8) tuple.
    """
    with open(path, "rb") as f:
        fmt, elements = _parse_header(f)
        endian = "<" if fmt in ("binary_little_endian", "ascii") else ">"
        verts = None
        colors = None
        for name, count, props in elements:
            has_list = any(p[1] is None for p in props)
            if fmt == "ascii":
                if name == "vertex":
                    names = [p[0] for p in props]
                    rows = [f.readline().split() for _ in range(count)]
                    arr = np.array(rows, dtype=np.float64)
                    ix = [names.index(c) for c in ("x", "y", "z")]
                    verts = arr[:, ix]
                    if return_colors and all(c in names for c in ("red", "green", "blue")):
                        ic = [names.index(c) for c in ("red", "green", "blue")]
                        colors = arr[:, ic].astype(np.uint8)
                else:
                    for _ in range(count):
                        f.readline()
            else:
                if has_list:
                    # ragged element (faces): must walk it item by item to skip
                    for _ in range(count):
                        for _, dt, list_dt in props:
                            if dt is None:
                                ct, it = list_dt
                                n = int(np.frombuffer(f.read(np.dtype(ct).itemsize), dtype=endian + ct)[0])
                                f.read(n * np.dtype(it).itemsize)
                            else:
                                f.read(np.dtype(dt).itemsize)
                    continue
                dtype = np.dtype([(p[0], endian + p[1]) for p in props])
                data = np.frombuffer(f.read(count * dtype.itemsize), dtype=dtype)
                if name == "vertex":
                    verts = np.stack([data["x"], data["y"], data["z"]], axis=1).astype(np.float64)
                    if return_colors and all(c in dtype.names for c in ("red", "green", "blue")):
                        colors = np.stack([data["red"], data["green"], data["blue"]], axis=1).astype(np.uint8)
    if verts is None:
        raise ValueError(f"no vertex element found in {path}")
    if return_colors:
        return verts, colors
    return verts


def read_ply_mesh(path: str):
    """Read a PLY mesh: vertices, triangle faces, and per-face scalar props.

    Returns ``(verts (N,3) float64, faces (F,3) int64, face_props dict)``.
    face_props maps scalar property names on the face element (e.g.
    ``category_id`` in Matterport house_segmentations meshes, reference
    preprocess/matterport3d/process.py:32-35) to (F,) arrays. Handles
    binary little/big endian and ascii; assumes uniform triangle faces on
    the fast path with a ragged fallback.
    """
    with open(path, "rb") as f:
        fmt, elements = _parse_header(f)
        endian = "<" if fmt in ("binary_little_endian", "ascii") else ">"
        verts = None
        faces = None
        face_props: dict[str, np.ndarray] = {}
        for name, count, props in elements:
            if fmt == "ascii":
                rows = [f.readline().split() for _ in range(count)]
                if name == "vertex":
                    names = [p[0] for p in props]
                    arr = np.array(rows, dtype=np.float64)
                    ix = [names.index(c) for c in ("x", "y", "z")]
                    verts = arr[:, ix]
                elif name == "face" and count:
                    out_faces, scalars = [], {p[0]: [] for p in props if p[1] is not None}
                    for row in rows:
                        pos = 0
                        for pname, dt, _list_dt in props:
                            if dt is None:
                                n = int(row[pos])
                                out_faces.append([int(v) for v in row[pos + 1:pos + 1 + n]])
                                pos += 1 + n
                            else:
                                scalars[pname].append(float(row[pos]))
                                pos += 1
                    # truncate polygons to their first triangle, matching the
                    # binary paths' (F,3) contract
                    faces = np.asarray([t[:3] for t in out_faces], dtype=np.int64)
                    face_props = {k: np.asarray(v) for k, v in scalars.items()}
                continue
            has_list = any(p[1] is None for p in props)
            if not has_list:
                dtype = np.dtype([(p[0], endian + p[1]) for p in props])
                data = np.frombuffer(f.read(count * dtype.itemsize), dtype=dtype)
                if name == "vertex":
                    verts = np.stack([data["x"], data["y"], data["z"]], axis=1).astype(np.float64)
                continue
            if count == 0:
                continue
            # face-like element: try the uniform-triangle fast path first
            start = f.tell()
            (lname, _, (ct, it)) = next(p for p in props if p[1] is None)
            n0 = int(np.frombuffer(f.read(np.dtype(ct).itemsize), dtype=endian + ct)[0])
            f.seek(start)
            fields = []
            for pname, dt, list_dt in props:
                if dt is None:
                    fields.append(("_n", endian + list_dt[0]))
                    fields.append(("_idx", endian + list_dt[1], (n0,)))
                else:
                    fields.append((pname, endian + dt))
            dtype = np.dtype(fields)
            raw = f.read(count * dtype.itemsize)
            # a ragged element can leave fewer bytes than the uniform guess
            # (e.g. a leading quad followed by triangles at EOF)
            uniform = len(raw) == count * dtype.itemsize
            data = np.frombuffer(raw, dtype=dtype) if uniform else None
            if not uniform or not np.all(data["_n"] == n0):  # ragged: slow walk
                f.seek(start)
                out_faces, scalars = [], {p[0]: [] for p in props if p[1] is not None}
                for _ in range(count):
                    for pname, dt, list_dt in props:
                        if dt is None:
                            ct_, it_ = list_dt
                            n = int(np.frombuffer(f.read(np.dtype(ct_).itemsize), dtype=endian + ct_)[0])
                            out_faces.append(np.frombuffer(f.read(n * np.dtype(it_).itemsize), dtype=endian + it_).astype(np.int64))
                        else:
                            scalars[pname].append(np.frombuffer(f.read(np.dtype(dt).itemsize), dtype=endian + dt)[0])
                if name == "face":
                    faces = np.asarray([t[:3] for t in out_faces], dtype=np.int64)
                    face_props = {k: np.asarray(v) for k, v in scalars.items()}
                continue
            if name == "face":
                faces = data["_idx"][:, :3].astype(np.int64)
                face_props = {p[0]: np.ascontiguousarray(data[p[0]]) for p in props if p[1] is not None}
    if verts is None:
        raise ValueError(f"no vertex element found in {path}")
    if faces is None:
        faces = np.zeros((0, 3), dtype=np.int64)
    return verts, faces, face_props


def write_ply_points(path: str, points: np.ndarray, colors: np.ndarray | None = None) -> None:
    """Write an (N,3) point cloud as binary-little-endian PLY."""
    points = np.asarray(points, dtype=np.float32)
    n = len(points)
    fields = [("x", "<f4"), ("y", "<f4"), ("z", "<f4")]
    if colors is not None:
        fields += [("red", "u1"), ("green", "u1"), ("blue", "u1")]
    rec = np.empty(n, dtype=np.dtype(fields))
    rec["x"], rec["y"], rec["z"] = points[:, 0], points[:, 1], points[:, 2]
    if colors is not None:
        colors = np.asarray(colors, dtype=np.uint8)
        rec["red"], rec["green"], rec["blue"] = colors[:, 0], colors[:, 1], colors[:, 2]
    header = ["ply", "format binary_little_endian 1.0", f"element vertex {n}"]
    header += [f"property float {c}" for c in ("x", "y", "z")]
    if colors is not None:
        header += [f"property uchar {c}" for c in ("red", "green", "blue")]
    header.append("end_header")
    with open(path, "wb") as f:
        f.write(("\n".join(header) + "\n").encode("ascii"))
        f.write(rec.tobytes())
