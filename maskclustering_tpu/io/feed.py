"""Compact host->device feed for per-frame tensors.

The reference transfers f32 depth and int mask-id frames to the GPU as-is
(utils/mask_backprojection.py loads cv2 arrays into torch CUDA tensors).
But ScanNet-family depth is NATIVELY uint16 millimetres and CropFormer ids
are uint16, so shipping f32/int32 over the host->device link wastes 2-4x
the bytes: ~614 MB/scene at the 480x640 x 250-frame operating point vs
~308 MB packed. This module encodes frames to uint16 on host when (and
only when) the round trip is bit-exact, and decodes after upload with one
device-side cast+mul — so results are identical to the f32 path, which
remains the automatic fallback for synthetic/noisy depth that never was
millimetre-quantized.

Bit-exactness: loaders produce depth as ``raw_u16.astype(f32) * f32(1/s)``
(io/image.read_depth_png); the codec reconstructs ``raw_u16`` by rounding,
re-applies the identical f32 multiply, and compares — encoding only wins
when every element survives, so a lossless claim is verified, not assumed.
"""

from __future__ import annotations

import functools
from typing import Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

# depth quantization steps tried in order: millimetres (ScanNet/demo/TASMap
# PNG scale 1000, .sens exports), then 0.25 mm (ScanNet++ iPhone scale 4000)
_DEPTH_SCALES = (1000.0, 4000.0)

# The fused mesh step (parallel/sharded.py) carries the feed encoding in the
# dtype alone, so uint16 there means exactly ONE quantization; its encoder
# (parallel/batch.py) passes scales=(FUSED_FEED_DEPTH_SCALE,) so no other
# step can engage. Relaxing the fused path to more scales means threading
# the scale into build_fused_step, not widening this tuple.
FUSED_FEED_DEPTH_SCALE = 1000.0


def _roundtrips(arr: np.ndarray, scale: float) -> Tuple[bool, np.ndarray]:
    """(ok, quanta): uint16 quanta reproduce ``arr`` bit-exactly at ``scale``.

    Non-finite values fail the range comparisons (NaN compares False), so
    no separate finiteness pass is needed.
    """
    q = np.rint(arr * np.float32(scale))
    with np.errstate(invalid="ignore"):
        if not ((q >= 0) & (q <= 65535)).all():
            return False, q
    q16 = q.astype(np.uint16)
    return bool((q16.astype(np.float32) * np.float32(1.0 / scale) == arr).all()), q16


def encode_depth(depths: np.ndarray,
                 scales: Tuple[float, ...] = _DEPTH_SCALES) -> Tuple[np.ndarray, float]:
    """(encoded, scale): uint16 quanta when bit-exact, else (f32, 0.0).

    ``encoded.astype(f32) * f32(1/scale)`` reproduces the input exactly
    when scale > 0; scale == 0.0 means the f32 array passes through. A
    strided ~4k-element probe rejects never-quantized depth before any
    full-array pass, so the guaranteed-fallback case costs ~nothing.
    """
    depths = np.asarray(depths)
    if depths.dtype != np.float32:  # contract is f32 metres; anything else
        return np.asarray(depths, np.float32), 0.0  # passes through as f32
    flat = depths.ravel()
    probe = flat[:: max(flat.size // 4096, 1)]
    for scale in scales:
        if not _roundtrips(probe, scale)[0]:
            continue
        ok, q16 = _roundtrips(flat, scale)
        if ok:
            return q16.reshape(depths.shape), scale
    return depths, 0.0


# jitted so the 1/scale constant is BAKED into the program instead of
# being an implicit per-scene scalar host->device upload, and the
# cast+mul over the biggest per-scene tensor dispatches as one fused
# kernel instead of two eager ops (surfaced by the Family-3 transfer
# guard: the eager form raised "disallowed host-to-device transfer"
# inside the device phase). Inside a trace the jit inlines; results are
# bit-identical either way (same convert+multiply).
@functools.partial(jax.jit, static_argnames="scale")
def _decode_depth_jit(device_arr: jnp.ndarray, *, scale: float) -> jnp.ndarray:
    return device_arr.astype(jnp.float32) * jnp.float32(1.0 / scale)


def decode_depth(device_arr: jnp.ndarray, scale: float) -> jnp.ndarray:
    """Device-side inverse of encode_depth (no-op for the f32 fallback)."""
    if scale == 0.0:
        return device_arr
    return _decode_depth_jit(device_arr, scale=float(scale))


def encode_seg(segs: np.ndarray) -> np.ndarray:
    """uint16 when every id fits (CropFormer ids are uint16), else int32."""
    segs = np.asarray(segs)
    if segs.dtype == np.uint16:
        return segs
    if segs.size and (segs.min() >= 0) and (segs.max() <= 65535):
        return segs.astype(np.uint16)
    return np.asarray(segs, np.int32)


def decode_seg(device_arr: jnp.ndarray) -> jnp.ndarray:
    return device_arr.astype(jnp.int32)


def device_resident(arr) -> bool:
    """Is ``arr`` already a device array (vs host numpy)?

    The ownership predicate of the feed: frames that arrive HOST-side are
    uploaded by this codec into fresh buffers nobody else holds — callers
    may donate those into their consuming program. Device-resident frames
    (the synthetic bench renders directly in HBM) belong to the caller and
    must never be donated.
    """
    return isinstance(arr, jnp.ndarray) and not isinstance(arr, np.ndarray)


def to_device_frames(
    depths: Union[np.ndarray, jnp.ndarray],
    segs: Union[np.ndarray, jnp.ndarray],
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Upload (depths, segs) compactly; returns decoded device arrays.

    Arrays already on device (see ``device_resident``) pass through
    untouched.
    """
    from maskclustering_tpu import obs

    if device_resident(depths):
        d_dev = jnp.asarray(depths, jnp.float32)
    else:
        enc, scale = encode_depth(np.asarray(depths))
        obs.count_transfer("h2d", enc.nbytes, "associate.feed")
        d_dev = decode_depth(jnp.asarray(enc), scale)
    if device_resident(segs):
        s_dev = jnp.asarray(segs, jnp.int32)
    else:
        enc_s = encode_seg(np.asarray(segs))
        obs.count_transfer("h2d", enc_s.nbytes, "associate.feed")
        s_dev = decode_seg(jnp.asarray(enc_s))
    return d_dev, s_dev
