from maskclustering_tpu.io.ply import read_ply_points, write_ply_points
from maskclustering_tpu.io.image import read_depth_png, read_rgb, read_mask_png, resize_nearest

__all__ = [
    "read_ply_points",
    "write_ply_points",
    "read_depth_png",
    "read_rgb",
    "read_mask_png",
    "resize_nearest",
]
