#!/usr/bin/env bash
# Fresh TPU-VM setup for maskclustering_tpu (no container).
#
#   git clone <repo> && cd <repo> && bash deploy/setup_tpu_vm.sh
#
# Installs pinned deps into ./.venv, builds the native C++ library, runs a
# CPU-mesh smoke test, then a one-scene TPU smoke bench. The TPU analog of
# the reference's dockerfile (reference dockerfile:1-78) minus the CUDA
# model builds — 2D masks arrive as precomputed id-map PNGs.
set -euo pipefail
cd "$(dirname "$0")/.."

PY=${PYTHON:-python3}
$PY -m venv .venv
source .venv/bin/activate

pip install --upgrade pip
pip install -r deploy/requirements.txt
# TPU runtime (libtpu) — on a CPU-only box this still works, jax falls back
pip install "jax[tpu]==0.9.0" \
  -f https://storage.googleapis.com/jax-releases/libtpu_releases.html || \
  echo "[setup] jax[tpu] unavailable (CPU-only host?) — continuing with CPU jax"

echo "[setup] building native C++ runtime"
python -m maskclustering_tpu.native.build --force

echo "[setup] CPU-mesh smoke test"
JAX_PLATFORMS=cpu python -m pytest tests/test_pipeline.py tests/test_parallel.py -q -x

echo "[setup] one-scene smoke bench on the default backend"
python bench.py --frames 16 --boxes 6 --points 32768 --image-h 120 --image-w 160 \
  --repeats 1 --spacing 0.02 --distance-threshold 0.03

cat <<'DONE'
[setup] done. Typical next steps:
  source .venv/bin/activate
  # full benchmark at the ScanNet operating point:
  python bench.py
  # real data (after preprocessing, see maskclustering_tpu/preprocess/):
  python -m maskclustering_tpu.run --config scannet
DONE
